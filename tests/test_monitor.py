"""Streaming linearizability monitor (jepsen_tpu/monitor/): incremental
encoder equivalence, the chunk-size-independent verdict property,
end-to-end early abort through core.run, SIGKILL consistency with
salvage, campaign terminal outcomes, the interpreter's multi-subscriber
op tap, and planlint PL013."""

import glob
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from jepsen_tpu import analysis
from jepsen_tpu import client as jc
from jepsen_tpu import checker as cc
from jepsen_tpu import core
from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu import interpreter, nemesis, store
from jepsen_tpu import monitor as jmon
from jepsen_tpu.checker import checkers as cks
from jepsen_tpu.checker import jax_wgl, wgl
from jepsen_tpu.models import base as mbase
from jepsen_tpu.monitor.stream import StreamEncoder
from jepsen_tpu.robust import AbortLatch, ChainedLatch


@pytest.fixture(autouse=True)
def store_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))


SPEC = mbase.model_spec("cas-register")


def _history(falsify_at=None):
    """A deterministic 2-process cas-register history (~36 events):
    sequential writes/reads/cas that a real register would produce;
    ``falsify_at`` replaces that read's value with 77 (never written),
    making the history definitively non-linearizable from that point."""
    value = None
    events = []
    reads = 0
    for i in range(12):
        p = i % 2
        if i % 3 == 0:
            value = i + 1
            events.append({"type": "invoke", "process": p, "f": "write",
                           "value": value})
            events.append({"type": "ok", "process": p, "f": "write",
                           "value": value})
        elif i % 3 == 1:
            v = value
            reads += 1
            if falsify_at is not None and reads == falsify_at:
                v = 77
            events.append({"type": "invoke", "process": p, "f": "read",
                           "value": None})
            events.append({"type": "ok", "process": p, "f": "read",
                           "value": v})
        else:
            old, new = value, value + 100
            events.append({"type": "invoke", "process": p, "f": "cas",
                           "value": [old, new]})
            events.append({"type": "ok", "process": p, "f": "cas",
                           "value": [old, new]})
            value = new
    return events


def _feed(mon, hist):
    for op in hist:
        mon.offer(op)


# ---------------------------------------------------------------------------
# stream encoder: incremental encoding == offline encoding


def test_stream_encoder_matches_offline_encoding():
    from jepsen_tpu import history as h
    hist = _history()
    enc = StreamEncoder(SPEC)
    for i, op in enumerate(hist):
        enc.offer(op, i)
    e, st = enc.materialize()
    e2, st2 = SPEC.encode(h.index([h.Op(o) for o in hist]))
    assert len(e) == len(e2)
    assert (e.f == e2.f).all()
    assert (e.args == e2.args).all()
    assert (e.ret == e2.ret).all()
    assert (e.is_ok == e2.is_ok).all()
    # invoke/return indices re-rank inside the engines; relative order
    # is what must agree
    import numpy as np
    assert (np.argsort(e.invoke_idx) == np.argsort(e2.invoke_idx)).all()
    assert (st == st2).all()


def test_stream_encoder_fail_drop_and_info_and_open():
    enc = StreamEncoder(SPEC)
    ops = [
        {"type": "invoke", "process": 0, "f": "write", "value": 1},
        {"type": "ok", "process": 0, "f": "write", "value": 1},
        {"type": "invoke", "process": 1, "f": "cas", "value": [9, 9]},
        {"type": "fail", "process": 1, "f": "cas", "value": [9, 9]},
        {"type": "invoke", "process": 2, "f": "write", "value": 2},
        {"type": "info", "process": 2, "f": "write", "value": 2},
        {"type": "invoke", "process": 3, "f": "read", "value": None},
        # process 3 stays open
    ]
    for i, op in enumerate(ops):
        enc.offer(op, i)
    e, _ = enc.materialize()
    # fail dropped; ok + info + open-invoke remain
    assert len(e) == 3
    assert e.n_ok == 1
    from jepsen_tpu.history import INF_TIME
    assert sorted(e.return_idx.tolist()) == [1, INF_TIME, INF_TIME]


def test_stream_encoder_init_ops():
    enc = StreamEncoder(SPEC, init_ops=[{"f": "write", "value": 0}])
    ops = [{"type": "invoke", "process": 0, "f": "read", "value": None},
           {"type": "ok", "process": 0, "f": "read", "value": 0}]
    for i, op in enumerate(ops):
        enc.offer(op, i)
    e, st = enc.materialize()
    r = wgl.check_encoded(SPEC, e, st)
    assert r["valid"] is True   # read 0 only valid because of init write


# ---------------------------------------------------------------------------
# THE equivalence property: monitor verdict == offline verdict, for
# valid AND invalid histories, across chunk sizes 1/8/64


@pytest.mark.parametrize("chunk", [1, 8, 64])
@pytest.mark.parametrize("falsify", [None, 4])
def test_monitor_matches_offline_jax_wgl(chunk, falsify):
    hist = _history(falsify_at=falsify)
    e, st = SPEC.encode([dict(o, index=i) for i, o in enumerate(hist)])
    offline = jax_wgl.check_encoded(SPEC, e, st)
    assert offline["valid"] in (True, False)

    latch = ChainedLatch()
    mon = jmon.Monitor(SPEC, latch, chunk=chunk, engine="wgl").start()
    _feed(mon, hist)
    mon.stop()
    s = mon.summary()
    assert s["verdict"] is offline["valid"], (s, offline)
    if offline["valid"] is False:
        assert latch.is_set()
        assert latch.reason == "monitor-violation"
        assert isinstance(s["detected_at_index"], int)
        assert s["detection_latency_s"] is not None
    else:
        assert not latch.is_set()


def test_monitor_device_engine_agrees():
    """One pass on the real jax-wgl engine: the monitor's chunk checks
    run the device search over pow-2 padded prefixes."""
    hist = _history(falsify_at=4)
    latch = ChainedLatch()
    mon = jmon.Monitor(SPEC, latch, chunk=8, engine="jax-wgl").start()
    _feed(mon, hist)
    mon.stop()
    assert mon.summary()["verdict"] is False


def test_monitor_keyed_streams():
    """Independent [k v] tuples split into per-key encoders; the
    violation names its key."""
    t = independent.tuple_
    ops = []
    for k in ("a", "b"):
        ops += [
            {"type": "invoke", "process": 0, "f": "write",
             "value": t(k, 1)},
            {"type": "ok", "process": 0, "f": "write", "value": t(k, 1)},
            {"type": "invoke", "process": 1, "f": "read",
             "value": t(k, None)},
            {"type": "ok", "process": 1, "f": "read",
             "value": t(k, 1 if k == "a" else 42)},
        ]
    latch = ChainedLatch()
    mon = jmon.Monitor(SPEC, latch, chunk=1, engine="wgl",
                       keyed=True).start()
    _feed(mon, ops)
    mon.stop()
    s = mon.summary()
    assert s["verdict"] is False
    assert s["key"] == "b"
    assert s["keys"] == 2


# ---------------------------------------------------------------------------
# end-to-end: violation aborts the run before the generator is done


class StaleRegister(jc.Client):
    """Applies the first ``apply_n`` writes, silently drops the rest
    (acked-but-lost): reads then expose staleness."""

    def __init__(self, apply_n=3):
        self.apply_n = apply_n
        self.value = None
        self.n = 0
        self.lock = threading.Lock()

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        out = dict(op)
        with self.lock:
            if op["f"] == "write":
                self.n += 1
                if self.n <= self.apply_n:
                    self.value = op["value"]
                out["type"] = "ok"
            else:
                out["type"] = "ok"
                out["value"] = self.value
        return out


def _wr_gen():
    import itertools
    c = itertools.count(1)

    def g(test, ctx):
        n = next(c)
        if n % 2:
            return {"type": "invoke", "f": "write", "value": n}
        return {"type": "invoke", "f": "read"}

    return g


def _violating_test(**kw):
    t = {"name": "monitor-abort", "nodes": ["n1"], "concurrency": 1,
         "ssh": {"dummy?": True}, "client": StaleRegister(),
         "monitor": {"chunk": 4, "engine": "wgl"},
         "time-limit-s": 60,
         "generator": gen.clients(_wr_gen()),
         "checker": cks.linearizable({"model": "cas-register",
                                      "algorithm": "wgl"})}
    t.update(kw)
    return t


def test_monitor_aborts_run_and_offline_reproduces():
    t0 = time.monotonic()
    test = core.run(_violating_test())
    assert time.monotonic() - t0 < 30   # the generator is endless
    assert test["aborted"] == "monitor-violation"
    r = test["results"]
    assert r["salvaged"] is True
    assert r["abort-reason"] == "monitor-violation"
    m = r["monitor"]
    assert m["verdict"] is False
    assert isinstance(m["detected_at_index"], int)
    assert m["detection_latency_s"] is not None
    # replaying the salvaged history through the offline checker
    # reproduces the invalid verdict
    assert r["valid"] is False
    d = store.path(test)
    hist = store.load_history({"name": test["name"],
                               "start-time": test["start-time"]})
    e, st = SPEC.encode(hist)
    assert wgl.check_encoded(SPEC, e, st)["valid"] is False
    with open(os.path.join(d, "monitor.json")) as f:
        assert json.load(f)["verdict"] is False
    # test.json keeps the monitor config but not the verdict blob
    with open(os.path.join(d, "test.json")) as f:
        tj = json.load(f)
    assert "monitor-verdict" not in tj
    assert tj.get("monitor") == {"chunk": 4, "engine": "wgl"}


def test_monitor_clean_run_stays_clean():
    """A healthy monitored run completes normally with verdict True
    and no abort."""
    test = core.run(_violating_test(
        client=StaleRegister(apply_n=10**9),
        generator=gen.clients(gen.limit(20, _wr_gen()))))
    assert not test.get("aborted")
    r = test["results"]
    assert r["valid"] is True
    assert r["monitor"]["verdict"] is True
    assert r["monitor"]["ops_consumed"] >= 20
    assert "salvaged" not in r


def test_monitor_skip_offline_handoff():
    test = core.run(_violating_test(
        monitor={"chunk": 4, "engine": "wgl", "skip-offline?": True}))
    r = test["results"]
    assert r["valid"] is False
    assert r["monitor-only"] is True
    assert r["monitor"]["verdict"] is False


def test_monitor_disables_without_linearizable_gate():
    """A checker family with no incremental engine: the monitor
    disables itself and the run completes untouched."""
    test = core.run(_violating_test(
        checker=cc.unbridled_optimism(),
        generator=gen.clients(gen.limit(10, _wr_gen()))))
    assert not test.get("aborted")
    assert "monitor" not in test["results"]


def test_all_unknown_checks_degrade_verdict(monkeypatch):
    """A monitor that never decided must summarize "unknown", never
    True -- with skip-offline? that summary would otherwise be
    recorded as the run's validity with no check ever deciding."""
    from jepsen_tpu.monitor import engine as mengine

    monkeypatch.setattr(
        mengine, "check_prefix",
        lambda *a, **kw: {"valid": "unknown", "error": "budget"})
    import jepsen_tpu.monitor.core as mcore
    latch = ChainedLatch()
    mon = mcore.Monitor(SPEC, latch, chunk=1, engine="wgl").start()
    _feed(mon, _history())
    mon.stop()
    s = mon.summary()
    assert s["verdict"] == "unknown"
    assert s["unknown_checks"] > 0
    assert not latch.is_set()


def test_later_definite_check_covers_earlier_unknown(monkeypatch):
    """Prefix-closure: a later True re-decides a key whose earlier
    chunk overflowed to "unknown"."""
    from jepsen_tpu.monitor import engine as mengine
    real = mengine.check_prefix
    flaky = {"n": 0}

    def sometimes_unknown(*a, **kw):
        flaky["n"] += 1
        if flaky["n"] == 1:
            return {"valid": "unknown", "error": "budget"}
        return real(*a, **kw)

    monkeypatch.setattr(mengine, "check_prefix", sometimes_unknown)
    latch = ChainedLatch()
    import jepsen_tpu.monitor.core as mcore
    mon = mcore.Monitor(SPEC, latch, chunk=1, engine="wgl").start()
    hist = _history()
    _feed(mon, hist[:8])
    deadline = time.monotonic() + 10
    while mon.checks < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert mon.checks >= 1
    _feed(mon, hist[8:])
    mon.stop()
    s = mon.summary()
    assert s["verdict"] is True
    assert s["unknown_checks"] == 1


def test_external_abort_still_works_on_monitored_run():
    """Flipping the BASE latch (campaign SIGINT path) aborts a
    monitored run with the external reason, not the monitor's."""
    base = AbortLatch()
    timer = threading.Timer(0.5, base.set, args=("SIGINT",))
    timer.start()
    try:
        test = core.run(_violating_test(
            client=StaleRegister(apply_n=10**9), abort=base,
            name="mon-ext-abort"))
    finally:
        timer.cancel()
    assert test["aborted"] == "SIGINT"
    r = test["results"]
    assert r["salvaged"] is True
    assert r["abort-reason"] == "SIGINT"
    # the monitor saw only a valid prefix
    assert r["monitor"]["verdict"] is True


# ---------------------------------------------------------------------------
# chained latch


def test_chained_latch_parent_and_own():
    parent = AbortLatch()
    chained = ChainedLatch(parent)
    assert not chained.is_set()
    parent.set("SIGINT")
    assert chained.is_set()
    assert chained.reason == "SIGINT"
    chained.set("monitor-violation")
    assert chained.reason == "monitor-violation"   # own reason wins
    assert not parent.is_set() or parent.reason == "SIGINT"


def test_chained_latch_does_not_leak_to_parent():
    parent = AbortLatch()
    chained = ChainedLatch(parent)
    chained.set("monitor-violation")
    assert chained.is_set()
    assert not parent.is_set()
    assert chained.wait(0.01)


# ---------------------------------------------------------------------------
# interpreter op-sink fan-out (the tap refactor)


def test_op_sinks_fan_out_with_journal():
    seen = []
    t = {"name": "tap", "start-time": store.local_time(),
         "concurrency": 2, "nodes": ["n1"],
         "client": StaleRegister(apply_n=10**9),
         "nemesis": nemesis.noop,
         "op-sinks": [seen.append],
         "generator": gen.clients(gen.limit(6, gen.repeat(
             {"f": "read"})))}
    t["journal"] = store.open_journal(t)
    h = interpreter.run(t)
    t["journal"].close()
    assert seen == h
    assert all("__op_serial__" not in o for o in seen)
    with open(store.path(t, store.JOURNAL_FILE)) as f:
        assert len(f.readlines()) == len(h)


def test_raising_sink_is_detached_not_fatal():
    calls = []

    def bad_sink(op):
        calls.append(op)
        raise RuntimeError("sink boom")

    t = {"concurrency": 1, "nodes": ["n1"],
         "client": StaleRegister(apply_n=10**9),
         "nemesis": nemesis.noop, "op-sinks": [bad_sink],
         "generator": gen.clients(gen.limit(4, gen.repeat(
             {"f": "read"})))}
    h = interpreter.run(t)
    assert len(h) == 8
    assert len(calls) == 1   # detached after the first raise


# ---------------------------------------------------------------------------
# SIGKILL mid-run: journal + monitor state consistent with salvage


_KILL9_CHILD = """
import os, sys, time, itertools
sys.path.insert(0, sys.argv[2])
from jepsen_tpu import client as jc, core, generator as gen, store
from jepsen_tpu.checker import checkers as cks
store.base_dir = sys.argv[1]

class SlowClient(jc.Client):
    def invoke(self, test, op):
        time.sleep(0.01)
        out = dict(op)
        out["type"] = "ok"
        out["value"] = None
        return out

core.run({"name": "kill9-mon", "nodes": ["n1"], "concurrency": 1,
          "ssh": {"dummy?": True}, "client": SlowClient(), "obs?": False,
          "monitor": {"chunk": 2, "engine": "wgl"},
          "checker": cks.linearizable({"model": "cas-register",
                                       "algorithm": "wgl"}),
          "generator": gen.clients(gen.repeat({"f": "read"}))})
"""


def test_kill9_monitored_run_salvageable(tmp_path):
    base = str(tmp_path / "store")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               JEPSEN_PYTEST_TIMEOUT_S="0")
    proc = subprocess.Popen(
        [sys.executable, "-c", _KILL9_CHILD, base, repo],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        pattern = os.path.join(base, "kill9-mon", "*",
                               store.JOURNAL_FILE)
        deadline = time.monotonic() + 60
        journal = None
        while time.monotonic() < deadline:
            hits = glob.glob(pattern)
            if hits and os.path.getsize(hits[0]) > 400:
                journal = hits[0]
                break
            time.sleep(0.05)
        assert journal, "child never journaled any ops"
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()

    run_dir = os.path.dirname(journal)
    # nothing finalized: the journal is the only history artifact, and
    # no half-written monitor verdict may shadow the salvage story
    assert not os.path.exists(os.path.join(run_dir, "history.jsonl"))
    assert not os.path.exists(os.path.join(run_dir, "monitor.json"))
    with open(journal) as f:
        ops = [json.loads(ln) for ln in f if ln.strip()]
    assert len(ops) >= 2
    # the journaled prefix replays through the offline checker exactly
    # like any salvaged history (reads of None against an empty
    # register are valid)
    e, st = SPEC.encode([dict(o, index=i) for i, o in enumerate(ops)])
    assert wgl.check_encoded(SPEC, e, st)["valid"] is True


# ---------------------------------------------------------------------------
# campaign: monitor abort is a terminal outcome; --resume skips it


def test_campaign_monitor_abort_terminal_and_not_resumed():
    from jepsen_tpu import campaign
    built = {"bad": 0, "good": 0}

    def build_bad(params):
        built["bad"] += 1
        return _violating_test(name="cell-bad")

    def build_good(params):
        built["good"] += 1
        return _violating_test(
            name="cell-good", client=StaleRegister(apply_n=10**9),
            generator=gen.clients(gen.limit(10, _wr_gen())))

    cells = [{"id": "bad", "build": build_bad, "params": {}},
             {"id": "good", "build": build_good, "params": {}}]
    report = campaign.run_cells(cells, campaign_id="mon-camp",
                                parallel=1)
    recs = {r["cell"]: r
            for r in store.latest_campaign_records("mon-camp")}
    assert recs["bad"]["outcome"] is False
    assert recs["bad"]["abort-reason"] == "monitor-violation"
    assert recs["good"]["outcome"] is True
    assert report["status"] == "complete"

    # resume: both cells are terminal; neither builds again
    before = dict(built)
    campaign.run_cells(cells, campaign_id="mon-camp", parallel=1,
                       resume=True)
    assert built == before


def test_campaign_monitored_cell_uses_device_slot(monkeypatch):
    """The scheduler hands monitored cells the device-slot semaphore."""
    from jepsen_tpu import campaign
    seen = {}

    def fake_run(test):
        seen["sem"] = test.get("monitor-device-sem")
        test["results"] = {"valid": True}
        return test

    cells = [{"id": "c", "test": _violating_test(
        name="slotted", generator=gen.clients(gen.limit(2, _wr_gen())))}]
    campaign.run_cells(cells, campaign_id="slot-camp", parallel=1,
                       run_fn=fake_run)
    assert seen["sem"] is not None
    assert hasattr(seen["sem"], "acquire")


# ---------------------------------------------------------------------------
# planlint PL013


def _plan(**kw):
    t = {"name": "pl013", "nodes": ["n1"], "concurrency": 1,
         "ssh": {"dummy?": True}, "client": StaleRegister(),
         "generator": gen.clients(gen.limit(1, gen.repeat(
             {"f": "read"}))),
         "checker": cks.linearizable({"model": "cas-register",
                                      "algorithm": "wgl"})}
    t.update(kw)
    return core.prepare_test(t)


def _codes(diags, severity=None):
    return [d.code for d in diags
            if severity is None or d.severity == severity]


def test_pl013_non_positive_chunk_is_error():
    diags = analysis.lint_plan(_plan(monitor={"chunk": 0}))
    assert "PL013" in _codes(diags, "error")
    diags = analysis.lint_plan(_plan(monitor={"chunk": -3}))
    assert "PL013" in _codes(diags, "error")
    diags = analysis.lint_plan(_plan(monitor={"chunk": 2.5}))
    assert "PL013" in _codes(diags, "error")


def test_pl013_orphan_chunk_warns():
    diags = analysis.lint_plan(_plan(**{"monitor-chunk": 8}))
    assert "PL013" in _codes(diags, "warning")


def test_pl013_no_incremental_engine_warns():
    diags = analysis.lint_plan(_plan(monitor=True,
                                     checker=cc.unbridled_optimism()))
    assert "PL013" in _codes(diags, "warning")


def test_pl013_unknown_engine_warns():
    diags = analysis.lint_plan(_plan(monitor={"engine": "pallas"}))
    assert "PL013" in _codes(diags, "warning")


def test_pl013_op_timeout_interaction_warns():
    diags = analysis.lint_plan(_plan(monitor=True,
                                     **{"op-timeout-ms": 500,
                                        "time-limit-s": 60}))
    assert "PL013" in _codes(diags, "warning")


def test_pl013_clean_monitor_plan():
    diags = analysis.lint_plan(_plan(monitor={"chunk": 64,
                                              "engine": "jax-wgl"}))
    assert "PL013" not in _codes(diags)


def test_monitor_config_normalization():
    assert jmon.config({}) is None
    assert jmon.config({"monitor": True}) == {}
    assert jmon.config({"monitor": 16}) == {"chunk": 16}
    assert jmon.config({"monitor": {"chunk": 8}}) == {"chunk": 8}
    assert jmon.config({"monitor": True,
                        "monitor-chunk": 32}) == {"chunk": 32}


def test_find_linearizable_walks_wrappers():
    lin = cks.linearizable({"model": "cas-register"})
    comp = cc.compose({"workload": independent.checker(
        cc.compose({"linearizable": lin, "stats": cks.stats()})),
        "stats": cks.stats()})
    got, keyed = jmon.find_linearizable(comp)
    assert got is lin
    assert keyed is True
    got, keyed = jmon.find_linearizable(lin)
    assert got is lin
    assert keyed is False
    got, keyed = jmon.find_linearizable(cks.stats())
    assert got is None
