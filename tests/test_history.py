import numpy as np

from jepsen_tpu import history as h
from jepsen_tpu.models import cas_register_spec, register_spec


def test_op_attr_access():
    o = h.op("invoke", 0, "read", None)
    assert o.type == "invoke"
    assert o.process == 0
    assert o["f"] == "read"
    o2 = o.assoc(type="ok", value=3)
    assert o2.type == "ok" and o2.value == 3
    assert o.type == "invoke"  # original untouched


def test_index():
    hist = [h.invoke_op(0, "read"), h.ok_op(0, "read", 1)]
    idx = h.index(hist)
    assert [o["index"] for o in idx] == [0, 1]


def test_pairs():
    hist = h.index([
        h.invoke_op(0, "read"),
        h.invoke_op(1, "write", 3),
        h.ok_op(1, "write", 3),
        h.ok_op(0, "read", 3),
        h.invoke_op(2, "read"),  # never completes
    ])
    ps = h.pairs(hist)
    assert len(ps) == 3
    assert ps[0][0]["process"] == 1 and ps[0][1]["type"] == "ok"
    assert ps[1][0]["process"] == 0
    assert ps[2] == (hist[4], None)


def test_complete_fills_read_values():
    hist = h.index([
        h.invoke_op(0, "read", None),
        h.ok_op(0, "read", 5),
    ])
    c = h.complete(hist)
    assert c[0]["value"] == 5


def test_encode_drops_fails_and_marks_info():
    hist = h.index([
        h.invoke_op(0, "write", 1),
        h.invoke_op(1, "write", 2),
        h.fail_op(1, "write", 2),
        h.ok_op(0, "write", 1),
        h.invoke_op(2, "write", 3),
        h.info_op(2, "write", 3),
    ])
    e, s0 = register_spec.encode(hist)
    assert len(e) == 2  # fail dropped
    assert e.n_ok == 1
    # info op has infinite return
    info_row = int(np.argmax(~e.is_ok))
    assert e.return_idx[info_row] == h.INF_TIME
    assert s0.tolist() == [h.NIL]


def test_encode_sorted_by_invoke():
    hist = h.index([
        h.invoke_op(0, "write", 1),
        h.invoke_op(1, "read", None),
        h.ok_op(0, "write", 1),
        h.ok_op(1, "read", 1),
    ])
    e, _ = cas_register_spec.encode(hist)
    assert list(e.invoke_idx) == sorted(e.invoke_idx)
    assert len(e) == 2


def test_parse_compact():
    hist = h.parse_history_edn_like([
        ("invoke", 0, "write", 1),
        ("ok", 0, "write", 1),
    ])
    assert hist[0]["index"] == 0
    assert hist[1]["type"] == "ok"
