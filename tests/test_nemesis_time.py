"""Clock nemesis tests: shim compilation command stream, op handling,
and generator shapes (reference nemesis/time.clj; the C shims themselves
are compile-checked and exercised locally)."""

import os
import random
import re
import subprocess
import tempfile
import time as wall

import pytest

from jepsen_tpu import control as c
from jepsen_tpu.control.remotes import DummyRemote
from jepsen_tpu.nemesis import time as nt

RES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "jepsen_tpu", "resources")


class ScriptedRemote(DummyRemote):
    """Dummy remote that answers date/bump-time probes with real-looking
    clock output and reports the shim binaries as absent."""

    def connect(self, conn_spec):
        return ScriptedRemote(conn_spec.get("host"), self.log)

    def execute(self, ctx, action):
        out = super().execute(ctx, action)
        cmd = out.get("cmd", "")
        if "test -e" in cmd:
            out["exit"] = 1   # shims not installed yet
        elif "date +%s.%N" in cmd:
            out["out"] = f"{wall.time():.9f}\n"
        elif "bump-time" in cmd and not cmd.endswith(".c"):
            out["out"] = f"{wall.time() + 0.5:.9f}\n"
        elif "strobe-time" in cmd and not cmd.endswith(".c"):
            out["out"] = "42\n"
        return out


def scripted_test(nodes=("n1", "n2", "n3")):
    log = []
    return {"nodes": list(nodes), "remote": ScriptedRemote(log=log),
            "dummy-log": log}


def test_compile_tools_command_stream():
    test = scripted_test(["n1"])
    with c.ssh_scope(test), c.on("n1"):
        nt.compile_tools()
    cmds = [cmd for _, cmd in test["dummy-log"]]
    assert any("mkdir -p /opt/jepsen" in x for x in cmds)
    assert any(x.startswith("upload") and "strobe-time.c" in x for x in cmds)
    assert any(x.startswith("upload") and "bump-time.c" in x for x in cmds)
    gccs = [x for x in cmds if "gcc" in x]
    assert len(gccs) == 2 and all("cd /opt/jepsen" in x for x in gccs)


def test_clock_nemesis_invoke_bump_and_check():
    test = scripted_test()
    nem = nt.clock_nemesis()
    with c.ssh_scope(test):
        nem.setup(test)
        op = {"type": "info", "process": "nemesis", "f": "bump",
              "value": {"n1": 4000, "n3": -250}}
        done = nem.invoke(test, op)
        check = nem.invoke(test, {"type": "info", "process": "nemesis",
                                  "f": "check-offsets"})
        nem.teardown(test)
    assert set(done["clock_offsets"]) == {"n1", "n3"}
    assert all(isinstance(v, float) for v in done["clock_offsets"].values())
    # bump ran the shim only on the targeted nodes
    bumps = [(h, cmd) for h, cmd in test["dummy-log"]
             if re.search(r"/opt/jepsen/bump-time '?-?\d", cmd)]
    assert sorted(h for h, _ in bumps) == ["n1", "n3"]
    assert any("sudo" in cmd for _, cmd in bumps)
    assert set(check["clock_offsets"]) == {"n1", "n2", "n3"}
    # teardown ntpdates every node
    ntp = [h for h, cmd in test["dummy-log"] if "ntpdate" in cmd]
    assert set(ntp) >= {"n1", "n2", "n3"}


def test_clock_nemesis_strobe_targets_and_args():
    test = scripted_test()
    nem = nt.clock_nemesis()
    with c.ssh_scope(test):
        nem.setup(test)
        op = {"type": "info", "process": "nemesis", "f": "strobe",
              "value": {"n2": {"delta": 100, "period": 5, "duration": 2}}}
        done = nem.invoke(test, op)
    strobes = [(h, cmd) for h, cmd in test["dummy-log"]
               if re.search(r"/opt/jepsen/strobe-time \d", cmd)]
    assert [h for h, _ in strobes] == ["n2"]
    assert re.search(r"strobe-time 100 5 2", strobes[0][1])
    assert set(done["clock_offsets"]) == {"n2"}


def test_generators_shapes():
    rng = random.Random(45100)
    random.seed(45100)
    test = {"nodes": ["a", "b", "c", "d", "e"]}
    r = nt.reset_gen(test, None)
    assert r["f"] == "reset" and set(r["value"]) <= set(test["nodes"])
    assert len(r["value"]) >= 1
    b = nt.bump_gen(test, None)
    assert b["f"] == "bump"
    for node, delta in b["value"].items():
        assert node in test["nodes"]
        assert 4 <= abs(delta) <= 2 ** 18 * 1.01
    s = nt.strobe_gen(test, None)
    assert s["f"] == "strobe"
    for node, spec in s["value"].items():
        assert 4 <= spec["delta"] <= 2 ** 18 * 1.01
        assert 1 <= spec["period"] <= 1024
        assert 0 <= spec["duration"] <= 32


def test_clock_gen_starts_with_check_offsets():
    from jepsen_tpu import generator as gen
    from jepsen_tpu.generator.testing import perfect, simulate
    g = gen.limit(5, nt.clock_gen())
    test = {"nodes": ["n1", "n2"], "concurrency": 1}
    hist = simulate(test, g, perfect)
    infos = [o for o in hist if o["type"] == "info"]
    assert infos[0]["f"] == "check-offsets"
    assert all(o["f"] in {"check-offsets", "reset", "bump", "strobe"}
               for o in infos)


@pytest.mark.parametrize("src", ["bump-time.c", "strobe-time.c"])
def test_shims_compile(src):
    with tempfile.TemporaryDirectory() as d:
        out = os.path.join(d, "a.out")
        r = subprocess.run(["gcc", "-Wall", "-Werror", "-O2",
                            os.path.join(RES, src), "-o", out],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        # running without args prints usage and exits 1
        r2 = subprocess.run([out], capture_output=True, text=True)
        assert r2.returncode == 1
        assert "usage" in r2.stderr
