"""Live-process integration tests: the control==node rig.

The reference proves its control plane against a real 5-node cluster
(docker/README.md:1-27, core_test.clj:122-177). This image has no SSH
stack and no container runtime, so these tests run the LocalRemote
topology instead: commands execute on the control host for real --
start-stop-daemon, grepkill, SIGSTOP/SIGCONT, file upload, gcc compiles
-- against N live toystore server processes (jepsen_tpu/suites/
toystore.py). Everything above the transport is the same code an SSH
cluster would run; tests/test_integration_ssh.py exercises the wire
itself where an sshd exists.
"""

import os

import pytest

from jepsen_tpu import control as c
from jepsen_tpu import core
from jepsen_tpu.suites import toystore


@pytest.fixture(autouse=True)
def _isolated_store(tmp_path, monkeypatch):
    from jepsen_tpu import store
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))


def _opts(tmp_path, base_port, **kw):
    opts = {
        "nodes": ["n1", "n2", "n3"],
        "time-limit": 5,
        "base-port": base_port,
        "scratch-dir": str(tmp_path / "nodes"),
        "algorithm": "competition",
    }
    opts.update(kw)
    return opts


def _store_dir(test):
    import pathlib

    from jepsen_tpu import store
    return pathlib.Path(store.path(test))


def test_toystore_end_to_end_with_kill_nemesis(tmp_path):
    """Full lifecycle against 3 live daemons with a kill/restart
    nemesis: deploy, daemonize, kill -9, restart with WAL recovery,
    check linearizability, snarf real log files."""
    test = toystore.toystore_test(_opts(tmp_path, 37110,
                                        **{"nemesis-mode": "kill"}))
    test = core.run(test)
    res = test["results"]
    assert res["valid"] is True, res
    hist = test["history"]
    oks = [o for o in hist if o.get("type") == "ok"
           and o.get("process") != "nemesis"]
    assert len(oks) >= 20, "live ops actually ran"
    # the nemesis really killed nodes: its ops carry per-node results
    nem = [o for o in hist if o.get("process") == "nemesis"
           and o.get("type") == "info" and o.get("f") == "start"]
    assert nem, "nemesis ran"
    # real log files snarfed off the nodes into the store dir
    d = _store_dir(test)
    logs = [p for p in (d / "n1").glob("*") if p.name == "toystore.log"] \
        if (d / "n1").exists() else []
    assert logs and "boot node=0" in logs[0].read_text()
    # no server processes left behind (axww: plain ps truncates argv
    # at the terminal width and the scratch path sits past it, which
    # would make this assertion pass vacuously)
    left = os.popen(
        "ps axww -o args= | grep toystore.py | grep -v grep").read()
    assert str(tmp_path) not in left


def test_toystore_stale_reads_detected(tmp_path):
    """The --stale server serves follower reads from an async local copy
    lagging 300 ms behind the primary: a REAL consistency bug the
    checker must catch, with the knossos-style witness attached."""
    test = toystore.toystore_test(_opts(
        tmp_path, 37130, concurrency=6, stale=True,
        **{"nemesis-mode": "none", "time-limit": 8}))
    test = core.run(test)
    res = test["results"]
    assert res["valid"] is False, res
    lin = res["linear"]
    assert lin["op"]["f"] in ("read", "cas")
    assert lin["final_paths"], "witness path attached"


def test_clock_shims_compile_and_run_on_node(tmp_path, monkeypatch):
    """The clock nemesis's compile-on-node recipe (upload C source, gcc
    -O2) against the real filesystem + compiler; the binaries execute
    (usage errors only -- nobody actually skews this machine's clock)."""
    from jepsen_tpu.nemesis import time as ntime
    monkeypatch.setattr(ntime, "DIR", str(tmp_path / "jepsen-bin"))
    test = {"nodes": ["n1"], "ssh": {"local?": True}}
    with core.with_sessions(test):
        with c.on("n1"):
            ntime.compile_tools()
            for tool in ("bump-time", "strobe-time"):
                assert os.path.exists(f"{ntime.DIR}/{tool}")
                # running without args must fail with usage, not crash
                res = c.exec_star(f"{ntime.DIR}/{tool}")
                assert res["exit"] != 0
                assert "usage" in (res["out"] + res["err"]).lower()


def test_daemon_helpers_against_live_process(tmp_path):
    """start_daemon / daemon_running / stop_daemon / grepkill drive a
    real background process through its lifecycle."""
    from jepsen_tpu.control import util as cu
    test = {"nodes": ["n1"], "ssh": {"local?": True}}
    script = tmp_path / "spin.sh"
    script.write_text("#!/bin/bash\nwhile true; do sleep 0.2; done\n")
    script.chmod(0o755)
    pidfile = str(tmp_path / "spin.pid")
    with core.with_sessions(test):
        with c.on("n1"):
            assert cu.start_daemon(str(script), pidfile=pidfile,
                                   logfile=str(tmp_path / "spin.log"))
            assert cu.daemon_running(pidfile)
            cu.stop_daemon(pidfile=pidfile)
            assert not cu.daemon_running(pidfile)


def test_toystore_setup_clears_zombie_daemons(tmp_path):
    """A daemon leaked by a predecessor run that died without teardown
    (crashed worker, kill -9) keeps its port bound and serves stale
    state; every later run's reads would hit the zombie and fail
    linearizability with phantom values. Setup must clear the port's
    owner first (observed live: a pthread-fatal pytest abort leaked 3
    daemons that then failed every subsequent pause-nemesis run)."""
    import socket as _socket
    import subprocess
    import sys
    import time as _time

    base = 37170
    zdir = tmp_path / "zombie"
    zdir.mkdir()
    (zdir / "toystore.py").write_text(toystore.SERVER_SRC)
    # the zombie binds node n1's port with NO peers (its own primary)
    # and gets fed a phantom value a fresh test could never explain
    z = subprocess.Popen(
        [sys.executable, str(zdir / "toystore.py"), "--port", str(base),
         "--node-id", "0", "--peers", "", "--data-dir", str(zdir)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        for _ in range(50):
            try:
                with _socket.create_connection(("127.0.0.1", base), 1) as s:
                    s.sendall(b"W x 9\n")
                    assert s.makefile().readline().strip() == "OK"
                break
            except OSError:
                _time.sleep(0.1)
        else:
            pytest.fail("zombie never came up")
        test = toystore.toystore_test(_opts(tmp_path, base))
        test = core.run(test)
        assert test["results"]["valid"] is True, test["results"]
        ps = subprocess.run(
            ["bash", "-c", "ps aux | grep toystor[e]"],
            capture_output=True, text=True).stdout
        assert z.poll() is not None, \
            f"setup must have killed zombie pid {z.pid}; ps:\n{ps}"
    finally:
        if z.poll() is None:
            z.kill()


@pytest.mark.parametrize("mode", ["pause"])
def test_toystore_pause_nemesis(tmp_path, mode):
    """SIGSTOP/SIGCONT nemesis against live daemons: paused nodes stall
    or fail ops; the system stays linearizable throughout."""
    test = toystore.toystore_test(_opts(
        tmp_path, 37150, **{"nemesis-mode": mode, "time-limit": 5}))
    test = core.run(test)
    assert test["results"]["valid"] is True, test["results"]


def test_toystore_set_workload_end_to_end(tmp_path):
    """Tutorial chapter 8 live: unique adds under a pause nemesis, heal,
    then every thread reads the set back; the set checker classifies
    every element and nothing acknowledged may be lost."""
    test = toystore.toystore_test(_opts(tmp_path, 37160, **{
        "workload": "set", "nemesis-mode": "pause", "time-limit": 4}))
    test = core.run(test)
    res = test["results"]
    assert res["valid"] is True, res
    assert res["lost-count"] == 0
    assert res["ok-count"] >= 5, res
    hist = test["history"]
    reads = [o for o in hist if o.get("type") == "ok"
             and o.get("f") == "read"]
    assert reads, "final reads ran after the heal phase"


def test_toystore_register_indep_workload(tmp_path):
    """Tutorial chapter 6 live: the register test lifted over
    independent keys with concurrent_generator; ops carry [k v] tuples
    and the per-key verdicts merge."""
    test = toystore.toystore_test(_opts(tmp_path, 37170, **{
        "workload": "register-indep", "nemesis-mode": "none",
        "concurrency": 4, "time-limit": 4, "ops-per-key": 12}))
    test = core.run(test)
    res = test["results"]
    assert res["valid"] is True, res
    assert res["results"], "per-key verdicts present"
