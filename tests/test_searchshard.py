"""ONE search sharded across the mesh (parallel/searchshard.py).

The last SURVEY §7 promise: partition a single history's DFS across
devices with per-device dedup tables and a collective steal ring
(all_gather work-balance vector + ppermute hand-off). These tests run
on the 8-virtual-CPU-device mesh from conftest and check the sharded
engine against the single-device engine and the CPU oracle on
histories large enough to need real iteration counts."""

import random

import pytest

import jax

from jepsen_tpu import models
from jepsen_tpu.checker import jax_wgl, wgl
from jepsen_tpu.parallel import check_encoded_sharded
from jepsen_tpu.simulate import corrupt, random_history


def _mesh(n=8):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"need {n} devices, have {len(devs)}")
    from jax.sharding import Mesh
    import numpy as np
    return Mesh(np.array(devs[:n]), ("search",))


def _inrange(hist):
    for o in hist:
        if o["type"] == "ok" and o["f"] == "read" \
                and isinstance(o.get("value"), int):
            o["value"] = o["value"] % 4
    return hist


def test_sharded_matches_single_device_verdicts():
    """Valid, invalid (exhaustion proof), and oracle-checked random
    histories all decide identically on the 8-shard mesh and the
    1-device engine."""
    mesh = _mesh()
    spec = models.cas_register_spec
    rng = random.Random(45100)
    decided_invalid = 0
    for trial in range(6):
        hist = random_history(rng, "cas-register", n_procs=6,
                              n_ops=160, crash_p=0.05)
        if trial % 2:
            hist = _inrange(corrupt(rng, hist))
        e, st = spec.encode(hist)
        single = jax_wgl.check_encoded(spec, e, st,
                                       rollout_kernel="scan")
        shard = check_encoded_sharded(spec, e, st, mesh)
        assert shard["valid"] == single["valid"], trial
        assert shard.get("engine", "aspect") in ("aspect", "jax-wgl",
                                                 "jax-wgl-sharded")
        if shard["valid"] is False:
            decided_invalid += 1
            # invalid verdicts carry a merged witness
            assert shard["configs"], trial
        oracle = wgl.check_encoded(spec, e, st)
        assert shard["valid"] == oracle["valid"], trial
    assert decided_invalid, "no exhaustion proof exercised"


def test_sharded_steal_spreads_work():
    """An exhaustion proof big enough to need >100 iterations must
    genuinely use the mesh: the steal ring feeds every starving shard,
    so exploration counts are non-zero beyond shard 0."""
    mesh = _mesh()
    spec = models.cas_register_spec
    rng = random.Random(11)
    hist = _inrange(corrupt(rng, random_history(
        rng, "cas-register", n_procs=10, n_ops=300, crash_p=0.1)))
    e, st = spec.encode(hist)
    single = jax_wgl.check_encoded(spec, e, st, rollout_kernel="scan")
    assert single.get("iterations", 0) > 100, \
        "history too easy to exercise sharding"
    shard = check_encoded_sharded(spec, e, st, mesh)
    assert shard["valid"] == single["valid"]
    assert shard["engine"] == "jax-wgl-sharded"
    busy = [x for x in shard["shard_explored"] if x > 0]
    assert len(busy) >= 4, shard["shard_explored"]


def test_sharded_mutex_and_register():
    """Model coverage beyond cas: mutex + plain register verdicts
    agree with the single-device engine."""
    mesh = _mesh()
    rng = random.Random(7)
    for name, spec in (("mutex", models.mutex_spec),
                       ("register", models.register_spec)):
        for trial in range(2):
            hist = random_history(rng, name, n_procs=6, n_ops=120,
                                  crash_p=0.05)
            if trial:
                hist = _inrange(corrupt(rng, hist))
            e, st = spec.encode(hist)
            single = jax_wgl.check_encoded(spec, e, st,
                                           rollout_kernel="scan")
            shard = check_encoded_sharded(spec, e, st, mesh)
            assert shard["valid"] == single["valid"], (name, trial)


def test_sharded_via_linearizable_checker():
    """The public gate: algorithm jax-wgl with engine_opts {"mesh"}
    routes one single-key search through the sharded engine."""
    from jepsen_tpu import history as h
    from jepsen_tpu.checker import checkers as ck
    from jepsen_tpu.checker import core as cc
    mesh = _mesh()
    inv, ok = h.invoke_op, h.ok_op
    good = [inv(0, "write", 1), ok(0, "write", 1),
            inv(1, "read"), ok(1, "read", 1)]
    bad = [inv(0, "write", 1), ok(0, "write", 1),
           inv(1, "read"), ok(1, "read", 2),
           inv(0, "write", 2), ok(0, "write", 2)]
    c = ck.linearizable({"model": "cas-register",
                         "algorithm": "jax-wgl",
                         "engine_opts": {"mesh": mesh}})
    assert cc.check(c, {}, good)["valid"] is True
    assert cc.check(c, {}, bad)["valid"] is False


def test_sharded_timeout_returns_unknown():
    mesh = _mesh()
    spec = models.cas_register_spec
    # the steal test's seed: needs hundreds of iterations, so a
    # 1-iteration budget cannot decide it
    rng = random.Random(11)
    hist = _inrange(corrupt(rng, random_history(
        rng, "cas-register", n_procs=10, n_ops=300, crash_p=0.1)))
    e, st = spec.encode(hist)
    r = check_encoded_sharded(spec, e, st, mesh, timeout_s=0,
                              chunk_iters=1)
    assert r["valid"] == "unknown"
    assert r["error"] == "timeout"
