"""fs-cache, faketime, charybdefs, and membership nemesis tests
(reference test/jepsen/fs_cache_test.clj + the nemesis/membership and
charybdefs recipes)."""

import threading
import time as wall

import pytest

from jepsen_tpu import control as c
from jepsen_tpu import faketime, fs_cache
from jepsen_tpu.control.remotes import DummyRemote


@pytest.fixture(autouse=True)
def cache_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setattr(fs_cache, "dir", str(tmp_path / "cache"))


def dummy_test(nodes=("n1",)):
    log = []
    return {"nodes": list(nodes), "ssh": {"dummy?": True},
            "dummy-log": log}


# -- fs-cache ----------------------------------------------------------------

def test_path_encoding_distinguishes_types_and_nesting():
    assert fs_cache.fs_path(["foo"]) == ["fs_foo"]
    assert fs_cache.fs_path(["foo", "bar"]) == ["ds_foo", "fs_bar"]
    assert fs_cache.fs_path([1]) == ["fl_1"]
    assert fs_cache.fs_path([True]) == ["fb_true"]
    assert fs_cache.fs_path(["a/b"]) == ["fs_a\\/b"]
    with pytest.raises(ValueError):
        fs_cache.fs_path([])
    with pytest.raises(TypeError):
        fs_cache.fs_path("not-a-seq")


def test_string_roundtrip_and_cached():
    path = ["db", "license"]
    assert not fs_cache.cached(path)
    assert fs_cache.load_string(path) is None
    assert fs_cache.save_string("sekrit", path) == "sekrit"
    assert fs_cache.cached(path)
    assert fs_cache.load_string(path) == "sekrit"
    fs_cache.clear(path)
    assert not fs_cache.cached(path)


def test_data_roundtrip():
    data = {"nodes": ["a", "b"], "epoch": 3}
    fs_cache.save_data(data, ["cluster", "state"])
    assert fs_cache.load_data(["cluster", "state"]) == data


def test_file_roundtrip(tmp_path):
    src = tmp_path / "src.bin"
    src.write_bytes(b"\x00\x01binary")
    fs_cache.save_file(str(src), ["blobs", 7])
    f = fs_cache.load_file(["blobs", 7])
    assert f is not None
    with open(f, "rb") as fh:
        assert fh.read() == b"\x00\x01binary"


def test_clear_all():
    fs_cache.save_string("x", ["one"])
    fs_cache.save_string("y", ["two"])
    fs_cache.clear()
    assert not fs_cache.cached(["one"])
    assert not fs_cache.cached(["two"])


def test_locking_serializes():
    order = []

    def worker(i):
        with fs_cache.locking(["expensive"]):
            order.append(("in", i))
            wall.sleep(0.05)
            order.append(("out", i))

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # no interleaving: every "in" is immediately followed by its "out"
    for a, b in zip(order[::2], order[1::2]):
        assert a[0] == "in" and b[0] == "out" and a[1] == b[1]


def test_deploy_remote_guards_suspicious_paths():
    fs_cache.save_string("x", ["d"])
    with pytest.raises(ValueError, match="suspicious"):
        fs_cache.deploy_remote(["d"], "/etc")
    with pytest.raises(RuntimeError, match="not cached"):
        fs_cache.deploy_remote(["nope"], "/var/lib/db/data")


def test_deploy_remote_command_stream():
    fs_cache.save_string("data", ["deployable"])
    test = dummy_test()
    with c.ssh_scope(test), c.on("n1"):
        fs_cache.deploy_remote(["deployable"], "/var/lib/db/data")
    cmds = [cmd for _, cmd in test["dummy-log"]]
    assert any("rm -rf /var/lib/db/data" in x for x in cmds)
    assert any("mkdir -p /var/lib/db" in x for x in cmds)
    assert any(x.startswith("upload") for x in cmds)


# -- faketime ----------------------------------------------------------------

def test_faketime_script():
    s = faketime.script("/usr/bin/db", 30, 1.5)
    assert s.startswith("#!/bin/bash")
    assert 'faketime -m -f "+30s x1.5"' in s
    assert '/usr/bin/db "$@"' in s
    assert '"-5s' in faketime.script("/x", -5, 1.0).replace("x1.0", "")


def test_faketime_rand_factor():
    import random
    rng = random.Random(45100)
    draws = [faketime.rand_factor(2.5, rng) for _ in range(500)]
    assert max(draws) / min(draws) <= 2.5
    assert all(0 < d < 2 for d in draws)


class NoFileRemote(DummyRemote):
    """test -e always fails: wrap sees no prior wrapper."""

    def connect(self, conn_spec):
        return NoFileRemote(conn_spec.get("host"), self.log)

    def execute(self, ctx, action):
        out = super().execute(ctx, action)
        if "test -e" in out.get("cmd", ""):
            out["exit"] = 1
        return out


def test_faketime_wrap_moves_original_once():
    log = []
    test = {"nodes": ["n1"], "remote": NoFileRemote(log=log),
            "dummy-log": log}
    with c.ssh_scope(test), c.on("n1"):
        faketime.wrap("/usr/bin/db", 10, 1.2)
    cmds = [cmd for _, cmd in log]
    assert any("mv /usr/bin/db /usr/bin/db.no-faketime" in x for x in cmds)
    assert any(x.startswith("upload") and "/usr/bin/db" in x for x in cmds)
    assert any("chmod a+x /usr/bin/db" in x for x in cmds)


# -- charybdefs --------------------------------------------------------------

def test_charybdefs_cookbook_commands():
    from jepsen_tpu import charybdefs
    test = dummy_test()
    with c.ssh_scope(test), c.on("n1"):
        charybdefs.break_all()
        charybdefs.break_one_percent()
        charybdefs.clear()
    cmds = [cmd for _, cmd in test["dummy-log"]]
    assert any("--io-error" in x and "cookbook" in x for x in cmds)
    assert any("--probability" in x for x in cmds)
    assert any("--clear" in x for x in cmds)


# -- membership nemesis ------------------------------------------------------

def test_membership_package_lifecycle():
    """A toy state machine: nodes join one by one; views poll via the
    control plane; ops resolve once the view reflects them."""
    from jepsen_tpu.nemesis import membership as m

    class JoinState(m.State):
        def __init__(self, joined=frozenset(), target=()):
            self.joined = frozenset(joined)
            self.target = tuple(target)

        def node_view(self, test, node):
            return sorted(self.joined)

        def merge_views(self, test):
            views = [v for v in self.node_views.values() if v is not None]
            return sorted(set().union(*map(set, views))) if views else []

        def fs(self):
            return {"join"}

        def op(self, test):
            left = [n for n in self.target if n not in self.joined]
            if not left:
                return None
            if self.pending:
                return "pending"
            return {"type": "info", "f": "join", "value": left[0]}

        def invoke(self, test, op):
            out = dict(op)
            out["type"] = "info"
            return out

        def resolve_op(self, test, pair):
            inv, done = pair
            node = dict(inv).get("value")
            if node not in self.joined:
                return self.assoc(joined=self.joined | {node})
            return None

    test = dummy_test(["n1", "n2"])
    test["concurrency"] = 1
    pkg = m.package({"faults": {"membership"}, "interval": 0.01,
                     "membership": {"state": JoinState(
                         target=("n1", "n2")),
                         "node_view_interval": 0.05}})
    assert pkg is not None
    nem = pkg["nemesis"]
    with c.ssh_scope(test):
        nem.setup(test)
        # drive ops by hand: generator box shares nemesis state
        from jepsen_tpu import generator as gen
        ctx = gen.context(test)
        seen = []
        for _ in range(200):
            got = gen.gen_op(pkg["generator"], test, ctx)
            if got is None:
                break
            op, nxt = got
            pkg = dict(pkg, generator=nxt)
            if op is gen.PENDING or op == gen.PENDING:
                wall.sleep(0.01)
                continue
            seen.append(nem.invoke(test, dict(op)))
        nem.teardown(test)
    assert [o["value"] for o in seen] == ["n1", "n2"]
    assert nem.box["state"].joined == {"n1", "n2"}


def test_membership_package_disabled():
    from jepsen_tpu.nemesis import membership as m
    assert m.package({"faults": {"kill"}}) is None
