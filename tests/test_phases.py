"""Per-dispatch phase attribution (obs.phases), the idle-bubble
ledger (obs.bubbles), and the noise-aware perf trend gate (obs.trend).

The invariants pinned here are the ones the whole "where does the
wall go?" plane rests on:

* phase spans from one session are exactly contiguous and
  non-overlapping (the cursor design), so the bubble fold can treat
  every gap as real;
* ``compile`` is emitted ONLY for the device lap right after a
  compile-ledger miss;
* ``wgl.device_busy_s`` is the device-compute bracket when phases
  are measured and never exceeds the full dispatch-chunk wall
  (``wgl.chunk_s``) — the busy-honesty fix;
* bubble re-folds are byte-identical and the attribution math adds
  up;
* the trend comparator passes A/A, catches real drops, and refuses
  cross-environment baselines.
"""

import json
import time

import pytest

from jepsen_tpu import obs
from jepsen_tpu.models import cas_register_spec
from jepsen_tpu.obs import bubbles, trend
from jepsen_tpu.obs import phases as obs_phases
from jepsen_tpu.obs import search as obs_search
from jepsen_tpu.simulate import random_history


# ---------------------------------------------------------------------------
# PhaseSession unit behavior

def _phase_events(tr):
    return [e for e in tr.events()
            if e.get("ph") == "X" and e.get("cat") == "phase"]


def test_session_spans_contiguous_and_nonoverlapping():
    tr, reg = obs.Tracer(), obs.Registry()
    with obs.bind(tr, reg):
        ph = obs_phases.capture("unit")
        assert ph.enabled
        for phase in ("encode", "plan", "h2d", "device", "d2h",
                      "host"):
            time.sleep(0.002)
            ph.lap(phase)
    evs = sorted(_phase_events(tr), key=lambda e: e["ts"])
    assert [e["name"] for e in evs] == [
        f"wgl.phase.{p}" for p in ("encode", "plan", "h2d", "device",
                                   "d2h", "host")]
    for a, b in zip(evs, evs[1:]):
        # one cursor, one clock offset: exactly contiguous (float-us
        # rounding only)
        assert abs((a["ts"] + a["dur"]) - b["ts"]) < 1.0, (a, b)
    # both sink legs agree: counter seconds == span seconds
    for e in evs:
        phase = e["name"][len("wgl.phase."):]
        c = reg.counter_value("wgl.phase_s", phase=phase,
                              engine="unit")
        assert c == pytest.approx(e["dur"] / 1e6, rel=1e-6)
        assert ph.totals[phase] == pytest.approx(e["dur"] / 1e6,
                                                 rel=1e-6)


def test_compile_phase_only_after_ledger_miss():
    tr, reg = obs.Tracer(), obs.Registry()
    with obs.bind(tr, reg):
        ph = obs_phases.capture("unit")
        ph.note_compile(True)          # miss arms the next device lap
        ph.lap("device")
        ph.lap("device")               # disarmed: plain device again
        ph.note_compile(False)         # a hit arms nothing
        ph.lap("device")
    names = [e["name"] for e in sorted(_phase_events(tr),
                                       key=lambda e: e["ts"])]
    assert names == ["wgl.phase.compile", "wgl.phase.device",
                     "wgl.phase.device"]


def test_disabled_session_times_but_emits_nothing():
    # nothing bound: lap still returns the measured wall (callers
    # reuse the number for heartbeats) but no sink sees anything
    ph = obs_phases.capture("unit")
    assert not ph.enabled
    time.sleep(0.002)
    assert ph.lap("device") > 0.0
    assert ph.totals == {}

    # bound, but the run said phases? False: same contract
    tr, reg = obs.Tracer(), obs.Registry()
    with obs.bind(tr, reg), obs.sink_scope(tr, reg,
                                           {"phases?": False}):
        ph2 = obs_phases.capture("unit")
        assert not ph2.enabled
        assert ph2.lap("device") >= 0.0
        obs_phases.note_wait("unit", 0.1)
    assert _phase_events(tr) == []
    assert reg.snapshot()["counters"] == {}


def test_note_wait_emits_one_span_and_counter():
    tr, reg = obs.Tracer(), obs.Registry()
    with obs.bind(tr, reg):
        obs_phases.note_wait("unit", 0.25, owner="t1")
    evs = _phase_events(tr)
    assert len(evs) == 1 and evs[0]["name"] == "wgl.phase.wait"
    assert evs[0]["dur"] == pytest.approx(0.25e6, rel=1e-6)
    assert evs[0]["args"]["owner"] == "t1"
    assert reg.counter_value("wgl.phase_s", phase="wait",
                             engine="unit") == pytest.approx(0.25)
    # garbage wall is dropped, not crashed on
    with obs.bind(tr, reg):
        obs_phases.note_wait("unit", None)
        obs_phases.note_wait("unit", -3.0)
    assert reg.counter_value("wgl.phase_s", phase="wait",
                             engine="unit") == pytest.approx(0.25)


# ---------------------------------------------------------------------------
# busy honesty: device_s vs chunk_s

def test_heartbeat_device_s_repoints_busy():
    reg = obs.Registry()
    with obs.bind(None, reg):
        so = obs_search.capture()
        # phases measured: busy is the device-compute bracket
        so.heartbeat("jax-wgl", iteration=1, chunk_s=1.0,
                     device_s=0.2)
        # phases off (no device_s): busy falls back to the chunk wall
        so.heartbeat("jax-wgl", iteration=2, chunk_s=0.5)
    busy = reg.counter_value("wgl.device_busy_s", engine="jax-wgl")
    assert busy == pytest.approx(0.7)
    h = reg.snapshot()["histograms"]["wgl.chunk_s{engine=jax-wgl}"]
    assert h["count"] == 2 and h["sum"] == pytest.approx(1.5)
    assert busy <= h["sum"]


def _engine_invariants(tr, reg, engine):
    evs = _phase_events(tr)
    evs = [e for e in evs if e["args"].get("engine") == engine]
    assert evs, f"no phase spans for {engine}"
    phases = {e["name"][len("wgl.phase."):] for e in evs}
    assert phases <= set(obs_phases.PHASES), phases
    assert {"encode", "device", "d2h", "host"} <= phases, phases
    # non-overlap per (pid, tid) lane
    lanes = {}
    for e in evs:
        lanes.setdefault((e.get("pid"), e.get("tid")),
                         []).append(e)
    for lane in lanes.values():
        lane.sort(key=lambda e: e["ts"])
        for a, b in zip(lane, lane[1:]):
            assert a["ts"] + a["dur"] <= b["ts"] + 1.0, (a, b)
    # the satellite pin: busy (device bracket, compile included) can
    # never exceed the full chunk wall
    busy = reg.counter_value("wgl.device_busy_s", engine=engine)
    h = reg.snapshot()["histograms"].get(
        "wgl.chunk_s{engine=%s}" % engine, {})
    assert busy > 0 and h.get("count", 0) >= 1
    assert busy <= h["sum"] * 1.001 + 1e-6, (busy, h["sum"])
    # device+compile span wall is what the busy counter summed
    span_dev = sum(e["dur"] / 1e6 for e in evs
                   if e["name"] in ("wgl.phase.device",
                                    "wgl.phase.compile"))
    assert busy == pytest.approx(span_dev, rel=0.02, abs=0.005)


def test_single_key_engine_emits_phase_plane():
    from jepsen_tpu.checker import jax_wgl
    hist = random_history(__import__("random").Random(7),
                          "cas-register", n_procs=4, n_ops=60,
                          crash_p=0.0)
    e, st = cas_register_spec.encode(hist)
    tr, reg = obs.Tracer(), obs.Registry()
    with obs.bind(tr, reg):
        r = jax_wgl.check_encoded(cas_register_spec, e, st,
                                  chunk_iters=32)
    assert r["valid"] is True
    _engine_invariants(tr, reg, "jax-wgl")
    # second identical search: the compile ledger is now hot for this
    # shape, so no lap may be attributed to compile
    tr2, reg2 = obs.Tracer(), obs.Registry()
    with obs.bind(tr2, reg2):
        jax_wgl.check_encoded(cas_register_spec, e, st,
                              chunk_iters=32)
    assert not [ev for ev in _phase_events(tr2)
                if ev["name"] == "wgl.phase.compile"]


def test_batch_engine_emits_phase_plane():
    from jepsen_tpu.parallel import keyshard
    rng = __import__("random").Random(11)
    pairs = [cas_register_spec.encode(
        random_history(rng, "cas-register", n_procs=4, n_ops=50,
                       crash_p=0.0)) for _ in range(3)]
    tr, reg = obs.Tracer(), obs.Registry()
    with obs.bind(tr, reg):
        rs = keyshard.check_batch_encoded(cas_register_spec, pairs,
                                          chunk_iters=32)
    assert [r["valid"] for r in rs] == [True] * 3
    _engine_invariants(tr, reg, "jax-wgl-batch")


# ---------------------------------------------------------------------------
# bubble ledger

def _span(pid, ts_us, dur_us, phase, engine="e"):
    return {"ph": "X", "cat": "phase", "name": f"wgl.phase.{phase}",
            "pid": pid, "tid": 1, "ts": float(ts_us),
            "dur": float(dur_us), "args": {"engine": engine}}


def test_bubble_fold_attribution_math():
    events = [
        # episode 1: 0.4 s extent, 0.2 s device, idle fully named
        _span(1, 0, 100_000, "encode"),
        _span(1, 100_000, 200_000, "device"),
        _span(1, 300_000, 50_000, "d2h"),
        _span(1, 350_000, 50_000, "host"),
        # >1 s quiet, then episode 2 with an unbracketed 0.1 s gap
        _span(1, 2_000_000, 100_000, "device"),
        _span(1, 2_200_000, 100_000, "host"),
    ]
    led = bubbles.fold_events(events)
    assert led["lanes"] == 1 and led["episodes"] == 2
    assert led["device_s"] == pytest.approx(0.3)
    # ep1 idle 0.2 attributed 0.2; ep2 extent 0.3, idle 0.2,
    # attributed 0.1, residual 0.1 (the unbracketed gap)
    assert led["idle_s"] == pytest.approx(0.4)
    assert led["attributed_s"] == pytest.approx(0.3)
    assert led["residual_s"] == pytest.approx(0.1)
    assert led["attribution_frac"] == pytest.approx(0.75)
    # the quiet stretch is reported but OUTSIDE the denominator
    assert led["inter_episode_s"] == pytest.approx(1.6)
    assert led["phases"]["host"] == pytest.approx(0.15)
    assert led["engines"]["e"]["device_s"] == pytest.approx(0.3)


def test_bubble_fold_byte_deterministic(tmp_path):
    events = [_span(1, i * 1000, 900, p)
              for i, p in enumerate(("encode", "device", "d2h",
                                     "host") * 5)]
    led1 = bubbles.fold_events(events)
    led2 = bubbles.fold_events(list(reversed(events)))
    assert bubbles.dumps(led1) == bubbles.dumps(led2)
    out = bubbles.write_ledger(led1, str(tmp_path / "b.json"))
    with open(out) as f:
        assert f.read() == bubbles.dumps(led1)
    # "path" never reaches the canonical bytes
    led1["path"] = "somewhere"
    assert bubbles.dumps(led1) == bubbles.dumps(led2)
    # no phase spans -> empty ledger, not a crash
    assert bubbles.fold_events([])["episodes"] == 0


def test_bubble_fold_ignores_non_phase_events():
    events = [
        _span(1, 0, 100_000, "device"),
        {"ph": "X", "cat": "search", "name": "wgl.phase.device",
         "pid": 1, "tid": 1, "ts": 0.0, "dur": 9e9, "args": {}},
        {"ph": "M", "name": "process_name", "pid": 1, "args": {}},
    ]
    led = bubbles.fold_events(events)
    assert led["device_s"] == pytest.approx(0.1)
    assert led["episodes"] == 1


# ---------------------------------------------------------------------------
# trend gate

def _rec(best_samples, fp=None, rung="mini-cas-batch"):
    return {"t": 0, "fingerprint": fp or {"host": "a"},
            "rungs": {rung: {"metrics": {"ops_per_s":
                                         max(best_samples)},
                             "samples": {"ops_per_s":
                                         list(best_samples)}}}}


def test_trend_compare_quiet_floor():
    base = [_rec([100.0, 90.0]), _rec([98.0, 95.0])]
    # within the allowance (threshold 0.2 > measured noise 0.1)
    ok = trend.compare(base, _rec([85.0]))
    assert ok["compared"] == 1 and ok["regressions"] == []
    # a real drop: 60 < 100 * (1 - 0.2)
    bad = trend.compare(base, _rec([60.0]))
    assert len(bad["regressions"]) == 1
    r = bad["regressions"][0]
    assert r["metric"] == "ops_per_s"
    assert r["drop_frac"] == pytest.approx(0.4)
    # a noisy baseline widens its own allowance past the threshold
    noisy = [_rec([100.0, 50.0])]
    assert trend.compare(noisy, _rec([55.0]))["regressions"] == []


def test_trend_refuses_cross_environment_baselines():
    base = [_rec([100.0], fp={"host": "elsewhere"})]
    v = trend.compare(base, _rec([10.0], fp={"host": "here"}))
    assert v["regressions"] == [] and v["compared"] == 0
    assert v["baseline_records"] == 0
    assert v["skipped_mismatched_env"] == 1


def test_trend_record_load_and_gate_cli(tmp_path):
    p = str(tmp_path / "trend.jsonl")
    fp = {"host": "a"}
    trend.record(_rec([100.0, 95.0])["rungs"], path=p, fp=fp)
    trend.record(_rec([99.0])["rungs"], path=p, fp=fp, label="aa")
    recs = trend.load(p)
    assert len(recs) == 2 and recs[1]["label"] == "aa"
    assert recs[0]["fingerprint"] == fp
    assert trend.main(["gate", "--path", p]) == 0
    trend.record(_rec([40.0])["rungs"], path=p, fp=fp)
    assert trend.main(["gate", "--path", p]) == 1
    # < 2 records: refused, NOT failed (a fresh repo must gate clean)
    assert trend.main(["gate", "--path",
                       str(tmp_path / "empty.jsonl")]) == 0


def test_mini_bench_shape():
    rungs = trend.mini_bench(n_keys=2, n_ops=40, repeats=2)
    r = rungs["mini-cas-batch"]
    assert len(r["samples"]["ops_per_s"]) == 2
    assert r["metrics"]["ops_per_s"] == max(r["samples"]["ops_per_s"])
    assert 0.0 <= r["metrics"]["duty_cycle"] <= 1.0
    assert set(r["phase_s"]) <= set(obs_phases.PHASES)
    assert "device" in r["phase_s"]


def test_fingerprint_is_stable_and_jsonable():
    a, b = trend.fingerprint(), trend.fingerprint()
    assert a == b
    json.dumps(a)
    assert set(a) == {"hostname", "jax_platforms", "jax", "platform",
                      "device_count"}


# ---------------------------------------------------------------------------
# PL022

def test_pl022_lint_trend(tmp_path):
    from jepsen_tpu.analysis import planlint

    codes = planlint.lint_trend

    assert codes({}) == []
    # phases off while a consumer needs the spans
    errs = codes({"phases?": False, "profile?": True,
                  "bubbles?": True})
    assert len(errs) == 2
    assert all(d.code == "PL022" and d.severity == "error"
               for d in errs)
    assert codes({"phases?": True, "profile?": True}) == []
    # unreadable baseline
    missing = str(tmp_path / "nope.jsonl")
    errs = codes({"trend-baseline": missing})
    assert len(errs) == 1 and errs[0].severity == "error"
    # readable baseline from another environment: warning
    p = tmp_path / "trend.jsonl"
    p.write_text(json.dumps(
        {"t": 0, "fingerprint": {"hostname": "not-this-box"},
         "rungs": {}}) + "\n")
    warns = codes({"trend-baseline": str(p)})
    assert len(warns) == 1 and warns[0].severity == "warning"
    # same-environment baseline lints clean
    p.write_text(json.dumps(
        {"t": 0, "fingerprint": trend.fingerprint(),
         "rungs": {}}) + "\n")
    assert codes({"trend-baseline": str(p)}) == []
    # bad threshold
    for bad in (0, -1, "fast", True):
        assert codes({"trend-gate-threshold": bad}), bad
    assert codes({"trend-gate-threshold": 0.3}) == []
    # and lint_plan carries the pass (the fleet/campaign wiring)
    t = {"name": "x", "phases?": False, "profile?": True}
    assert any(d.code == "PL022" for d in planlint.lint_plan(t))


# ---------------------------------------------------------------------------
# fold surfaces

def test_introspection_summary_folds_phases_and_chunk():
    from jepsen_tpu.obs.merge import introspection_summary
    reg = obs.Registry()
    reg.inc("wgl.device_busy_s", 2.0, engine="jax-wgl")
    reg.inc("wgl.phase_s", 2.0, phase="device", engine="jax-wgl")
    reg.inc("wgl.phase_s", 0.5, phase="h2d", engine="jax-wgl")
    reg.observe("wgl.chunk_s", 3.0, engine="jax-wgl")
    out = introspection_summary(reg.snapshot())
    assert out["device_busy_s"]["jax-wgl"] == pytest.approx(2.0)
    assert out["chunk_s"]["jax-wgl"] == pytest.approx(3.0)
    assert out["phase_s"]["jax-wgl"] == {"device": 2.0, "h2d": 0.5}
    assert out["device_busy_s"]["jax-wgl"] <= out["chunk_s"]["jax-wgl"]
