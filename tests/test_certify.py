"""Proof-carrying verdicts (jepsen_tpu/analysis/certify.py): the
normalized witness schema pinned across engines, every seeded
mutation class caught by its VC code, the bounded cross-check and
differential harness, the checker/monitor/service/campaign wiring,
byte-deterministic certificate.json, planlint PL023, and — the
acceptance property — certification NEVER flips a verdict."""

import copy
import json
import os

import numpy as np
import pytest

import jax

from jepsen_tpu import core as jcore
from jepsen_tpu import history as h
from jepsen_tpu import store
from jepsen_tpu.analysis import certify, planlint
from jepsen_tpu.checker import core as ccore
from jepsen_tpu.checker import jax_wgl, linear, wgl, witness
from jepsen_tpu.checker.checkers import Linearizable
from jepsen_tpu.models import base as mbase

SPEC = mbase.model_spec("register")


@pytest.fixture(autouse=True)
def store_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))


# ---------------------------------------------------------------------------
# history builders


def _pairs(ops):
    """Sequential invoke/ok pairs: [(f, value), ...]."""
    ev, idx = [], 0
    for f, v in ops:
        ev.append({"index": idx, "type": "invoke", "process": 0,
                   "f": f, "value": None if f == "read" else v})
        idx += 1
        ev.append({"index": idx, "type": "ok", "process": 0,
                   "f": f, "value": v})
        idx += 1
    return ev


def valid_concurrent():
    """w1 || r=1: linearizable, undecidable without a real order."""
    return [
        {"index": 0, "type": "invoke", "process": 0, "f": "write",
         "value": 1},
        {"index": 1, "type": "invoke", "process": 1, "f": "read",
         "value": None},
        {"index": 2, "type": "ok", "process": 0, "f": "write",
         "value": 1},
        {"index": 3, "type": "ok", "process": 1, "f": "read",
         "value": 1},
    ]


def invalid_sequential():
    """w1; w2; r=1; r=2 sequentially: every read value was genuinely
    written (the state-abstraction fast path can't decide), but no
    total order satisfies both reads -> the real search runs and
    decides False."""
    return _pairs([("write", 1), ("write", 2), ("read", 1),
                   ("read", 2)])


def _certify(result, hist, test=None, samples=0, **kw):
    lin = Linearizable(SPEC)
    client = lin.prepare_history(h.client_ops(h.ensure_indexed(hist)))
    return certify.certify_with_diagnostics(
        SPEC, client, result, test=test, samples=samples, **kw)


def _codes(diags):
    return sorted({d.code for d in diags})


def _linear_result(hist, test=None):
    t = dict(test or {})
    return Linearizable(SPEC, algorithm="linear").check(
        t, h.ensure_indexed(hist), {}), t


# ---------------------------------------------------------------------------
# the normalized witness schema, pinned across engines


def test_witness_schema_linear_invalid():
    r, _ = _linear_result(invalid_sequential())
    assert r["valid"] is False
    w = r["witness"]
    assert w["schema"] == witness.WITNESS_SCHEMA == 1
    assert w["engine"] == "linear"
    assert w["verdict"] is False
    assert w["rows"] == 4 and w["n_ok"] == 4
    assert w["segment"] is None
    assert sorted(w["order"]) == sorted(w["linearized_rows"])


def test_witness_schema_jax_wgl_both_verdicts():
    """The device engine emits the same schema on BOTH verdicts (the
    valid path decodes the winning TOPK slot into a full witness)."""
    for hist, want in ((valid_concurrent(), True),
                       (invalid_sequential(), False)):
        e, st = SPEC.encode(h.ensure_indexed(hist))
        r = jax_wgl.check_encoded(SPEC, e, st)
        assert r["valid"] is want
        w = r["witness"]
        assert w["schema"] == 1 and w["engine"] == "jax-wgl"
        assert w["verdict"] is want
        assert w["rows"] == len(e) and w["n_ok"] == int(e.n_ok)
        if want:
            # a valid witness linearizes every ok row, replayably
            assert sorted(w["linearized_rows"]) == list(range(len(e)))
            assert sorted(w["order"]) == sorted(w["linearized_rows"])


def test_witness_schema_wgl_oracle():
    """The CPU WGL oracle attaches the same schema on False (no
    engine tag: it is the oracle, not a device engine)."""
    e, st = SPEC.encode(h.ensure_indexed(invalid_sequential()))
    r = wgl.check_encoded(SPEC, e, st)
    assert r["valid"] is False
    assert r["witness"]["schema"] == 1
    assert r["witness"]["verdict"] is False


def test_witness_schema_searchshard():
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip(f"need 2 devices, have {len(devs)}")
    from jax.sharding import Mesh
    from jepsen_tpu.parallel import check_encoded_sharded
    mesh = Mesh(np.array(devs[:2]), ("search",))
    e, st = SPEC.encode(h.ensure_indexed(invalid_sequential()))
    try:
        r = check_encoded_sharded(SPEC, e, st, mesh)
    except TypeError as exc:  # old jax lacks shard_map check_vma
        pytest.skip(f"sharded engine unavailable: {exc}")
    assert r["valid"] is False
    w = r["witness"]
    assert w["schema"] == 1 and w["engine"] == "jax-wgl-sharded"
    assert w["verdict"] is False


def test_clean_verdicts_certify_clean():
    """Soundness, direction one: untampered runs produce ZERO
    diagnostics (valid replays; invalid cross-checks confirmed)."""
    for hist in (valid_concurrent(), invalid_sequential()):
        e, st = SPEC.encode(h.ensure_indexed(hist))
        r = jax_wgl.check_encoded(SPEC, e, st)
        cert, diags = _certify(r, hist, samples=1)
        assert diags == [], [d.message for d in diags]
        names = {c["name"]: c for c in cert["checks"]}
        assert names["witness"]["status"] == "replayed"
        if r["valid"] is False:
            assert names["cross-check"]["status"] == "confirmed"


# ---------------------------------------------------------------------------
# mutation detection: every seeded tamper class raises its VC code


def test_vc001_illegal_transition():
    """Tampering the order to read-before-write keeps precedence legal
    but makes the model reject the read from the init state."""
    hist = valid_concurrent()
    e, st = SPEC.encode(h.ensure_indexed(hist))
    r = jax_wgl.check_encoded(SPEC, e, st)
    assert r["valid"] is True and r["witness"]["order"] == [0, 1]
    r["witness"]["order"] = [1, 0]
    _, diags = _certify(r, hist)
    assert _codes(diags) == ["VC001"]


def test_vc002_real_time_violation():
    """Swapping two SEQUENTIAL writes (both always legal) violates
    only real-time precedence."""
    hist = _pairs([("write", 1), ("write", 2), ("read", 2)])
    r, _ = _linear_result(hist)
    assert r["valid"] is True
    e, st = _encoded(hist)
    w = witness.build(SPEC, e, "linear", True, np.ones(3, bool), st)
    w["order"] = [1, 0, 2]
    r2 = dict(r, witness=w)
    _, diags = _certify(r2, hist)
    assert "VC002" in _codes(diags)


def _encoded(hist):
    e, st = SPEC.encode(h.ensure_indexed(hist))
    return e, st


def test_vc003_incomplete_valid_witness():
    hist = valid_concurrent()
    e, st = _encoded(hist)
    r = jax_wgl.check_encoded(SPEC, e, st)
    w = r["witness"]
    w["linearized_rows"] = [0]
    w["order"] = [0]
    _, diags = _certify(r, hist)
    assert "VC003" in _codes(diags)


def test_vc004_flipped_verdict():
    r, _ = _linear_result(invalid_sequential())
    r["witness"]["verdict"] = True
    _, diags = _certify(r, invalid_sequential())
    assert "VC004" in _codes(diags)


def test_vc005_malformed_witness():
    base, _ = _linear_result(invalid_sequential())
    for tamper in (
        lambda w: w.update(rows=99),
        lambda w: w.update(schema=2),
        lambda w: w.update(n_ok=1),
        lambda w: w.update(linearized_rows=[0, 0]),
        lambda w: w.update(order=[0, 0]),
        lambda w: w.update(linearized_rows=[0, 77]),
    ):
        r = copy.deepcopy(base)
        tamper(r["witness"])
        _, diags = _certify(r, invalid_sequential())
        assert "VC005" in _codes(diags), tamper


def test_vc006_device_verdict_without_witness():
    e, st = _encoded(invalid_sequential())
    r = jax_wgl.check_encoded(SPEC, e, st)
    r.pop("witness")
    _, diags = _certify(r, invalid_sequential())
    assert any(d.code == "VC006" and d.severity == "info"
               for d in diags)
    # CPU engines legitimately carry no witness: note, not finding
    r2 = {"valid": False, "engine": "linear"}
    _, d2 = _certify(r2, invalid_sequential())
    assert "VC006" not in _codes(d2)


def test_vc008_cross_check_refutes():
    """A valid history recorded as False is refuted by the
    independent engine."""
    hist = valid_concurrent()
    _, diags = _certify({"valid": False, "engine": "jax-wgl"}, hist)
    assert "VC008" in _codes(diags)


def test_vc009_budget_exhausted_is_info_not_fatal():
    r, _ = _linear_result(invalid_sequential())
    _, diags = _certify(r, invalid_sequential(), budget=1)
    vc9 = [d for d in diags if d.code == "VC009"]
    assert vc9 and all(d.severity == "info" for d in vc9)
    assert not [d for d in diags if d.severity == "error"]


def test_vc010_differential_divergence(monkeypatch):
    """A lying engine in the differential table is caught."""
    monkeypatch.setitem(certify.DIFF_ENGINES, "wgl",
                        lambda spec, e, st, budget: {"valid": True})
    r, _ = _linear_result(invalid_sequential())
    _, diags = _certify(r, invalid_sequential(), samples=1)
    assert "VC010" in _codes(diags)


def test_vc011_undecided_engine_is_info(monkeypatch):
    monkeypatch.setitem(certify.DIFF_ENGINES, "wgl",
                        lambda spec, e, st, budget: {"valid": "unknown"})
    r, _ = _linear_result(invalid_sequential())
    _, diags = _certify(r, invalid_sequential(), samples=1)
    assert any(d.code == "VC011" and d.severity == "info"
               for d in diags)


# ---------------------------------------------------------------------------
# segment provenance (VC007)


def _segmented_result(test=None):
    """A planned, merged result over a sequential history: each
    segment's witness built and provenance-stamped exactly like
    checkers._check_planned does."""
    from jepsen_tpu.analysis import searchplan
    hist = _pairs([("write", i) for i in range(1, 7)])
    client = h.client_ops(h.ensure_indexed(hist))
    min_seg = 2
    segs, _info = searchplan.plan_segments(SPEC, client, min_seg)
    assert len(segs) > 1, "history failed to segment"
    wits = []
    for i, s in enumerate(segs):
        e, st = SPEC.encode(s.events)
        w = witness.build(SPEC, e, "jax-wgl", True,
                          np.ones(len(e), bool), st)
        w["segment"] = {"index": i, "count": len(segs),
                        "seed": s.seed}
        wits.append(w)
    result = {"valid": True, "engine": "jax-wgl",
              "witnesses": wits,
              "searchplan": {"segments": len(segs)}}
    t = {"searchplan-min-segment": min_seg, **(test or {})}
    return result, client, t


def test_segments_recertify_clean():
    result, client, t = _segmented_result()
    cert, diags = certify.certify_with_diagnostics(
        SPEC, client, result, test=t, samples=0)
    assert diags == [], [d.message for d in diags]
    seg_checks = [c for c in cert["checks"]
                  if c["name"].startswith("witness.segment")]
    assert len(seg_checks) == result["searchplan"]["segments"]
    assert all(c["status"] == "replayed" for c in seg_checks)


def test_vc007_segment_provenance_mismatch():
    for tamper in (
        lambda r: r["witnesses"][1]["segment"].update(seed={"f": 9}),
        lambda r: r["witnesses"][1]["segment"].update(index=0),
        lambda r: r["witnesses"].pop(),
    ):
        result, client, t = _segmented_result()
        tamper(result)
        _, diags = certify.certify_with_diagnostics(
            SPEC, client, result, test=t, samples=0)
        assert "VC007" in _codes(diags), tamper


# ---------------------------------------------------------------------------
# checker.core wiring + THE containment property


def test_check_hook_builds_certificate():
    test = {}
    r = ccore.check(Linearizable(SPEC, algorithm="linear"), test,
                    invalid_sequential())
    assert r["valid"] is False
    cert = test["certificate"]
    assert cert["schema"] == 1 and cert["verdict"] is False
    assert cert["counts"]["error"] == 0
    rep = test["analysis"]["certify"]
    assert rep["counts"]["error"] == 0
    assert rep["summary"]["verdict"] is False
    assert test["certify-done?"]


def test_check_hook_opt_out():
    test = {"certify?": False}
    ccore.check(Linearizable(SPEC, algorithm="linear"), test,
                invalid_sequential())
    assert "certificate" not in test


def test_certification_never_flips_verdict(monkeypatch):
    """THE acceptance property: a certifier crash (or a FAILING
    certification) leaves the verdict and the result untouched."""
    def boom(*a, **k):
        raise RuntimeError("certifier bug")
    monkeypatch.setattr(certify, "certify_with_diagnostics", boom)
    for hist, want in ((valid_concurrent(), True),
                       (invalid_sequential(), False)):
        test = {}
        r = ccore.check(Linearizable(SPEC, algorithm="linear"),
                        test, hist)
        assert r["valid"] is want
        assert "certificate" not in test


def test_failing_certification_reports_but_does_not_flip():
    """A certificate that FAILS (flipped witness) is recorded with VC
    errors while the returned verdict stands."""
    test = {}
    lin = Linearizable(SPEC, algorithm="linear")
    real = lin.check

    def lying_check(t, hist, opts=None):
        r = real(t, hist, opts)
        if isinstance(r.get("witness"), dict):
            r["witness"]["verdict"] = not r["witness"]["verdict"]
        return r

    lin.check = lying_check
    r = ccore.check(lin, test, invalid_sequential())
    assert r["valid"] is False  # unflipped
    assert test["certificate"]["counts"]["error"] >= 1
    assert "VC004" in {d["code"] for d in
                       test["certificate"]["diagnostics"]}


# ---------------------------------------------------------------------------
# persistence: certificate.json, byte determinism, disk re-certification


def _persisted_run(hist, name="certrun"):
    test = {"name": name, "start-time": store.local_time(),
            "history": h.ensure_indexed(hist)}
    r = ccore.check(Linearizable(SPEC, algorithm="linear"), test,
                    test["history"])
    test["results"] = r
    store.save_2(test)
    return test, store.path(test)


def test_certificate_persisted_and_byte_deterministic():
    test, run_dir = _persisted_run(invalid_sequential())
    p = os.path.join(run_dir, "certificate.json")
    b1 = open(p, "rb").read()
    store.write_certificate(test)
    assert open(p, "rb").read() == b1
    cert = json.loads(b1)
    assert cert["verdict"] is False and cert["schema"] == 1


def test_certify_run_clean_and_tampered():
    _, run_dir = _persisted_run(invalid_sequential())
    summary, diags = certify.certify_run(run_dir)
    assert summary["certified"] and diags == []

    p = os.path.join(run_dir, "certificate.json")
    cert = json.load(open(p))
    cert["verdict"] = True
    cert["witness"]["verdict"] = True
    json.dump(cert, open(p, "w"))
    _, diags = certify.certify_run(run_dir)
    codes = _codes(diags)
    assert "VC012" in codes and "VC004" in codes

    # unreadable certificate: VC012, never a crash
    open(p, "w").write("{not json")
    _, diags = certify.certify_run(run_dir)
    assert "VC012" in _codes(diags)


def test_lint_driver_certify_exit_codes(tmp_path):
    import tools.lint as tl
    _, run_dir = _persisted_run(invalid_sequential())
    assert tl.run_certify(run_dir) == 0
    p = os.path.join(run_dir, "certificate.json")
    cert = json.load(open(p))
    cert["witness"]["order"] = list(reversed(cert["witness"]["order"]))
    json.dump(cert, open(p, "w"))
    assert tl.run_certify(run_dir) == 1
    assert tl.run_certify(str(tmp_path / "nope")) == 2


def test_certify_campaign_fold():
    _, d1 = _persisted_run(invalid_sequential(), name="cella")
    _, d2 = _persisted_run(valid_concurrent(), name="cellb")
    p = os.path.join(d1, "certificate.json")
    cert = json.load(open(p))
    cert["verdict"] = True
    json.dump(cert, open(p, "w"))
    block = certify.certify_campaign(
        [{"path": d1}, {"path": d2}, {"path": "/nope"}])
    assert block["sampled"] == 2 and block["of"] == 2
    assert block["counts"]["error"] >= 1
    assert "VC012" in block["codes"]
    bad = [r for r in block["runs"] if r["path"] == d1][0]
    assert "VC012" in bad["codes"]


def _keyed_hist():
    """Key 0 clean, key 1 non-linearizable, on distinct processes."""
    from jepsen_tpu import independent as ind
    ev = []
    for k, ops in ((0, [("write", 1), ("read", 1)]),
                   (1, [("write", 1), ("write", 2), ("read", 1),
                        ("read", 2)])):
        for f, v in ops:
            ev.append({"type": "invoke", "process": k * 2, "f": f,
                       "value": ind.tuple_(k,
                                           None if f == "read" else v)})
            ev.append({"type": "ok", "process": k * 2, "f": f,
                       "value": ind.tuple_(k, v)})
    return h.ensure_indexed(ev)


def test_keyed_workload_certifies_failing_key():
    """The independent checker's batched path certifies ONE
    deterministically chosen key (the failing one), records the key in
    the certificate context, and the disk path re-derives the same
    subhistory from the reloaded [k v] history."""
    from jepsen_tpu import independent as ind
    hist = _keyed_hist()
    test = {"name": "keyed-cert", "start-time": store.local_time(),
            "history": hist}
    chk = ind.checker(Linearizable(SPEC, algorithm="jax-wgl"))
    r = ccore.check(chk, test, hist)
    assert r["valid"] is False and r["failures"] == [1]
    cert = test["certificate"]
    assert cert["context"]["key"] == 1
    assert cert["verdict"] is False
    assert cert["counts"]["error"] == 0, cert["diagnostics"]

    test["results"] = r
    store.save_2(test)
    summary, diags = certify.certify_run(store.path(test))
    assert summary["certified"] and diags == [], \
        [d.message for d in diags]


def test_keyed_fallback_path_certifies_deterministically():
    """The per-key thread-pool fallback (CPU algorithm) must certify
    the same deterministically chosen key, not whichever subcheck
    finished first."""
    from jepsen_tpu import independent as ind
    hist = _keyed_hist()
    test = {}
    chk = ind.checker(Linearizable(SPEC, algorithm="linear"))
    r = ccore.check(chk, test, hist)
    assert r["valid"] is False
    assert test["certificate"]["context"]["key"] == 1
    assert test["certificate"]["counts"]["error"] == 0
    assert test["certify-done?"] is True


# ---------------------------------------------------------------------------
# monitor backstop


def test_certify_monitor_confirms_violation():
    e, st = _encoded(invalid_sequential())
    r = linear.check_encoded(SPEC, e, st)
    assert r["valid"] is False
    ev = {"spec": SPEC, "e": e, "init_state": st, "result": r,
          "key": 3}
    summary, diags = certify.certify_monitor(ev)
    assert summary["confirmed"] is True
    assert summary["counts"]["error"] == 0
    # independence: the linear-engined monitor cross-checks via wgl
    assert any(c.get("engine") == "wgl" for c in summary["checks"]
               if c["name"] == "cross-check")
    assert summary["key"] == "3"


def test_analyze_backstop_wiring():
    test = {"results": {"valid": False},
            "monitor-evidence": {
                "spec": SPEC, **dict(zip(("e", "init_state"),
                                         _encoded(invalid_sequential()))),
                "result": {"valid": False, "engine": "linear"},
                "key": None}}
    jcore._certify_monitor_verdict(test, {"verdict": False})
    mc = test["results"]["monitor-certification"]
    assert mc["confirmed"] is True and mc["counts"]["error"] == 0
    assert "monitor-evidence" not in test
    assert test["analysis"]["certify-monitor"]["verdict"] is False


def test_analyze_backstop_contained(monkeypatch):
    def boom(*a, **k):
        raise RuntimeError("backstop bug")
    monkeypatch.setattr(certify, "certify_monitor", boom)
    test = {"results": {"valid": False},
            "monitor-evidence": {"spec": SPEC}}
    jcore._certify_monitor_verdict(test, {"verdict": False})
    assert test["results"] == {"valid": False}


def test_monitor_parks_evidence():
    """A streaming monitor that detects a violation parks certifiable
    evidence, and finalize moves it onto the test map."""
    from jepsen_tpu import monitor as jmon
    test = {"monitor": {"chunk": 1, "engine": "linear"},
            "checker": Linearizable(SPEC, algorithm="linear"),
            "model": "register"}
    mon = jmon.install(test)
    if mon is None:
        pytest.skip("monitor could not start")
    for op in h.ensure_indexed(invalid_sequential()):
        mon.offer(dict(op))
    jmon.finalize(mon, test)
    assert test["monitor-verdict"]["verdict"] is False
    ev = test.get("monitor-evidence")
    assert ev is not None and ev["result"]["valid"] is False
    summary, _ = certify.certify_monitor(ev)
    assert summary["confirmed"] is True


# ---------------------------------------------------------------------------
# service path


def test_service_check_certify_payload():
    from jepsen_tpu.fleet import service
    hist = invalid_sequential()
    payload = {"history": hist, "model": "register",
               "engine": "linear", "certify": True}
    out = service._check_admitted(payload, hist)
    assert out["valid"] is False
    c = out["certify"]
    assert c["certified"] is True and c["verdict"] is False
    assert c["counts"]["error"] == 0
    assert not any(k.startswith("_") for k in out)


def test_service_check_certify_validation():
    from jepsen_tpu.fleet import service
    hist = valid_concurrent()
    with pytest.raises(service.ApiError):
        service._check_admitted({"history": hist, "model": "register",
                                 "engine": "linear", "certify": "yes"},
                                hist)
    out = service._check_admitted({"history": hist,
                                   "model": "register",
                                   "engine": "linear"}, hist)
    assert "certify" not in out


# ---------------------------------------------------------------------------
# planlint PL023


def test_pl023_bad_knobs_are_errors():
    diags = planlint.lint_certify({"certify": {"samples": 0,
                                               "budget": -5}})
    assert [d.code for d in diags] == ["PL023", "PL023"]
    assert all(d.severity == "error" for d in diags)
    assert planlint.lint_certify({"certify": "yes"})[0].severity == \
        "error"


def test_pl023_skip_offline_backstop_note():
    diags = planlint.lint_certify(
        {"monitor": {"skip-offline?": True}})
    assert [(d.code, d.severity) for d in diags] == [("PL023", "info")]
    # opted out: the note is moot, the knobs warn
    diags = planlint.lint_certify(
        {"certify?": False, "certify": {"samples": 2},
         "monitor": {"skip-offline?": True}})
    assert [(d.code, d.severity) for d in diags] == \
        [("PL023", "warning")]


def test_pl023_rides_lint_plan():
    diags = planlint.lint_plan(
        {"name": "x", "certify": {"budget": 0}})
    assert any(d.code == "PL023" and d.severity == "error"
               for d in diags)


def test_pl023_clean():
    assert planlint.lint_certify({}) == []
    assert planlint.lint_certify(
        {"certify": {"samples": 2, "budget": 1000}}) == []


# ---------------------------------------------------------------------------
# the budget knob reaches the certifier through the test map


def test_config_defaults_and_overrides():
    assert certify.config({}) == {"samples": certify.DEFAULT_SAMPLES,
                                  "budget": certify.DEFAULT_BUDGET}
    assert certify.config({"certify": {"samples": 3,
                                       "budget": 10}}) == \
        {"samples": 3, "budget": 10}
    # junk falls back to defaults (PL023 reports it; config contains)
    assert certify.config({"certify": {"samples": True,
                                       "budget": -1}}) == \
        {"samples": certify.DEFAULT_SAMPLES,
         "budget": certify.DEFAULT_BUDGET}
    assert certify.enabled({})
    assert not certify.enabled({"certify?": False})
    assert not certify.enabled({"analysis?": False})
