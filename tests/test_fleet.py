"""Fleet subsystem tests: the disk-persistent compile ledger, lease
tables + expiry watchdog, loopback worker dispatch (including the
kill -9 work-stealing acceptance test), the /api/ service routes with
the web.Handler hardening (413 before read), backend failover
tiering, and planlint PL014."""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from jepsen_tpu import robust, store, web
from jepsen_tpu.campaign import compile_cache, plan, scheduler
from jepsen_tpu.campaign.journal import CampaignJournal
from jepsen_tpu.analysis import planlint
from jepsen_tpu.fleet import backends as fbackends
from jepsen_tpu.fleet import dispatch, ledger as fledger, service
from jepsen_tpu.fleet import worker as fworker


@pytest.fixture(autouse=True)
def store_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))
    compile_cache.reset()
    service.reset()
    yield
    compile_cache.reset()
    service.reset()


# ---------------------------------------------------------------------------
# ledger: persistence, cross-process visibility, torn tails


def test_ledger_survives_process_restart():
    fledger.attach()
    assert compile_cache.note("e", ("spec", 64, True)) is False
    assert compile_cache.note("e", ("spec", 64, True)) is True
    # simulate a restart: wipe ALL in-memory state, re-attach from disk
    compile_cache.reset()
    fledger.attach()
    assert compile_cache.note("e", ("spec", 64, True)) is True
    s = compile_cache.stats()
    assert s["hits"] == 1 and s["misses"] == 0


def test_ledger_sees_sibling_process_appends():
    fledger.attach()
    # a "sibling process": an independent handle on the same file
    sibling = fledger.Ledger(store.compile_ledger_path())
    sibling.record("e", ("other-shape", 128))
    # never seen locally, but note() re-reads the file before a miss
    assert compile_cache.note("e", ("other-shape", 128)) is True


def test_ledger_cross_process_for_real(tmp_path):
    """An actual second python process appends; this one hits."""
    d = store.compile_ledger_path()
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "from jepsen_tpu.fleet import ledger as fl\n"
        "fl.Ledger(%r).record('e', ('from-child', 7))\n"
        % (os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), d))
    subprocess.run([sys.executable, "-c", code], check=True,
                   timeout=60)
    fledger.attach()
    assert compile_cache.note("e", ("from-child", 7)) is True


def test_ledger_torn_tail_and_fragment_tolerated():
    led = fledger.attach()
    led.record("e", ("good", 1))
    with open(led.path, "ab") as f:
        f.write(b'{"engine": "e", "key": [trunc')   # torn tail
    # a fresh reader skips the fragment but keeps the good line
    led2 = fledger.Ledger(led.dir)
    assert len(led2.refresh()) == 1
    # the next appender terminates the fragment in place
    led2.record("e", ("after-tear", 2))
    led3 = fledger.Ledger(led.dir)
    assert len(led3.refresh()) == 2
    assert led3.stats()["shapes"] == 2


def test_ledger_stats_aggregate_across_processes():
    led = fledger.attach()
    led.record("e", ("s1", 1))
    led.note_stats(5, 2)
    sibling = fledger.Ledger(led.dir)
    sibling.note_stats(3, 1)
    st = led.stats()
    assert st["hits"] == 8 and st["misses"] == 3
    assert st["shapes"] == 1


def test_ledger_attach_is_idempotent_per_dir():
    led = fledger.attach()
    assert fledger.attach() is led
    assert fledger.attached() is led
    fledger.detach(expected=fledger.Ledger(led.dir))  # not the live one
    assert fledger.attached() is led
    fledger.detach(expected=led)
    assert fledger.attached() is None


def test_canon_key_roundtrips_json_types():
    import numpy as np
    k = fledger.canon_key("e", ("spec", np.int64(64), True, 2.5))
    assert k == ("e", ("spec", 64, True, 2.5))
    # and equals the parse of its own serialized form
    rt = json.loads(json.dumps(list(k[1])))
    assert fledger.canon_key("e", rt) == k


def test_run_cells_reports_ledger_block():
    from jepsen_tpu import tests as tst
    t = tst.noop_test()
    t.update({"ssh": {"dummy?": True}, "obs?": False, "name": "led",
              "nodes": ["n1"], "concurrency": 1})
    rep = scheduler.run_cells([{"id": "a", "test": t}],
                              campaign_id="led")
    cc = rep["compile_cache"]
    assert "ledger" in cc and cc["ledger"]["path"].endswith(
        "ledger.jsonl")
    assert os.path.exists(cc["ledger"]["path"])
    # --no-ledger equivalent: no block, nothing on disk
    store.delete()
    compile_cache.reset()
    rep = scheduler.run_cells([{"id": "a", "test": dict(t)}],
                              campaign_id="led2", ledger=False)
    assert "ledger" not in rep["compile_cache"]


# ---------------------------------------------------------------------------
# journal events


def test_journal_events_never_fold_into_outcomes():
    jr = CampaignJournal("ev")
    jr.append_event({"event": "lease", "cell": "a", "worker": "w1"})
    assert jr.latest() == []           # a lease is not an outcome
    assert jr.completed() == {}
    jr.append_cell({"cell": "a", "outcome": True})
    jr.append_event({"event": "lease-expired", "cell": "a",
                     "worker": "w1"})
    latest = jr.latest()
    assert len(latest) == 1 and latest[0]["outcome"] is True
    assert "a" in jr.completed()       # the late event didn't resurrect
    assert [e["event"] for e in jr.events()] == ["lease",
                                                 "lease-expired"]
    with pytest.raises(AssertionError):
        jr.append_cell({"cell": "b", "event": "lease"})
    with pytest.raises(AssertionError):
        jr.append_event({"cell": "b", "outcome": True})


# ---------------------------------------------------------------------------
# planlint PL014


def _codes(diags):
    return [(d.code, d.severity) for d in diags]


def test_pl014_worker_rules():
    assert planlint.lint_fleet({"workers": ["a", "b"],
                                "lease-s": 600}) == []
    diags = planlint.lint_fleet({"workers": []})
    assert ("PL014", "error") in _codes(diags)
    diags = planlint.lint_fleet({"workers": ["a", ""]})
    assert any("empty worker" in d.message for d in diags)
    diags = planlint.lint_fleet({"workers": ["a", "a"]})
    assert any("duplicate worker" in d.message
               and d.severity == "error" for d in diags)


def test_pl014_lease_and_serve_rules():
    diags = planlint.lint_fleet({"lease-s": 0})
    assert ("PL014", "error") in _codes(diags)
    diags = planlint.lint_fleet({"lease-s": -5})
    assert ("PL014", "error") in _codes(diags)
    diags = planlint.lint_fleet({"serve?": True, "device-slots": 0})
    assert any("device slots" in d.message and d.severity == "error"
               for d in diags)
    # serve with a sane slot count is clean
    assert planlint.lint_fleet({"serve?": True,
                                "device-slots": 1}) == []


def test_pl014_backend_and_lease_vs_time_limit():
    diags = planlint.lint_fleet({"backends": ["tpu", "warp-drive"]})
    assert any("warp-drive" in d.message and d.severity == "error"
               for d in diags)
    assert planlint.lint_fleet({"backends": ["tpu", "cpu"]}) == []
    diags = planlint.lint_fleet({"lease-s": 10, "time-limit": 60})
    assert any(d.code == "PL014" and d.severity == "warning"
               and "outlives" in d.message for d in diags)


# ---------------------------------------------------------------------------
# robust.leases


def test_lease_table_stale_release_is_noop():
    t = robust.LeaseTable()
    l1 = t.grant("cell", "w1", 60)
    assert l1.attempt == 1
    l2 = t.grant("cell", "w2", 60)      # steal replaces
    assert l2.attempt == 2
    assert t.release(l1) is False       # stale holder can't release
    assert t.holder("cell") == "w2"
    assert t.release(l2) is True
    assert t.holder("cell") is None
    assert t.attempts("cell") == 2


def test_lease_watchdog_fires_once_per_expiry():
    t = robust.LeaseTable()
    fired = []
    wd = robust.LeaseWatchdog(t, fired.append, poll_s=0.02).start()
    try:
        t.grant("a", "w1", 0.01)
        deadline = time.monotonic() + 5
        while not fired and time.monotonic() < deadline:
            time.sleep(0.02)
        assert [lease.unit for lease in fired] == ["a"]
        time.sleep(0.1)                 # no re-fire: lease was removed
        assert len(fired) == 1
        assert t.holder("a") is None
    finally:
        wd.stop()


def test_lease_watchdog_contains_callback_crash():
    t = robust.LeaseTable()
    seen = []

    def boom(lease):
        seen.append(lease.unit)
        raise RuntimeError("buggy steal")

    wd = robust.LeaseWatchdog(t, boom, poll_s=0.02).start()
    try:
        t.grant("a", "w", 0.01)
        t.grant("b", "w", 0.01)
        deadline = time.monotonic() + 5
        while len(seen) < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert sorted(seen) == ["a", "b"]   # crash didn't kill the dog
    finally:
        wd.stop()


# ---------------------------------------------------------------------------
# parse_workers


def test_parse_workers_shapes():
    ws = dispatch.parse_workers("local,local,name=local,db1:22")
    assert [w.id for w in ws] == ["local", "local#2", "name", "db1:22"]
    assert ws[0].kind == "local" and ws[2].kind == "local"
    assert ws[3].kind == "ssh"
    ws = dispatch.parse_workers(["h1"], ssh={"username": "u",
                                             "port": 2222,
                                             "password": "ignored"})
    assert ws[0].conn_spec["username"] == "u"
    assert ws[0].conn_spec["port"] == 2222
    assert "password" not in ws[0].conn_spec


# ---------------------------------------------------------------------------
# dispatch: loopback fleet (real worker subprocesses)

NOOP_OPTS = {"nodes": ["n1"], "concurrency": 1, "ssh": {"dummy?": True},
             "time-limit": 1, "workload": "noop"}


def _noop_cells(n=2):
    return plan.expand({"axes": {"seed": list(range(n)),
                                 "workload": ["noop"]}})


def test_fleet_loopback_two_workers():
    rep = dispatch.run_fleet(
        _noop_cells(2), dispatch.parse_workers("local,local"),
        campaign_id="fl", base_options=NOOP_OPTS, lease_s=120,
        builder="jepsen_tpu.demo:demo_test")
    assert rep["status"] == "complete"
    assert rep["summary"]["outcomes"] == {"True": 2}
    assert rep["mode"] == "fleet"
    recs = store.latest_campaign_records("fl")
    assert {r["worker"] for r in recs} <= {"local", "local#2"}
    assert all(r.get("pid") not in (None, os.getpid()) for r in recs)
    leases = [e for e in store.campaign_events("fl")
              if e["event"] == "lease"]
    assert sorted(e["cell"] for e in leases) == \
        sorted(c["id"] for c in _noop_cells(2))
    meta = CampaignJournal("fl").load_meta()
    assert meta["mode"] == "fleet"
    assert meta["workers"] == ["local", "local#2"]


def test_fleet_worker_death_steals_cell(tmp_path):
    """The acceptance test: kill -9 one worker mid-cell; the cell is
    re-leased, re-run, and the journal shows exactly one terminal
    record per cell."""
    marker = str(tmp_path / "die-once")
    cells = _noop_cells(2)
    victim = cells[0]["id"]
    cells[0]["params"]["die-once-marker"] = marker
    rep = dispatch.run_fleet(
        cells, dispatch.parse_workers("local,local"),
        campaign_id="steal", base_options=NOOP_OPTS, lease_s=120,
        builder="jepsen_tpu.demo:demo_test")
    assert rep["status"] == "complete"
    assert os.path.exists(marker)       # the SIGKILL really happened
    recs = store.latest_campaign_records("steal")
    assert {r["cell"]: r["outcome"] for r in recs} == {
        c["id"]: True for c in cells}
    stolen = [r for r in recs if r["cell"] == victim][0]
    assert stolen["attempt"] == 2       # first lease died, second ran
    evs = store.campaign_events("steal")
    assert any(e["event"] == "lease-failed" and e["cell"] == victim
               for e in evs)
    assert len([e for e in evs if e["event"] == "lease"
                and e["cell"] == victim]) == 2
    # EXACTLY one terminal record per cell in the raw journal
    terminal = [r for r in store.load_campaign_records("steal")
                if not r.get("event")]
    per_cell = {}
    for r in terminal:
        per_cell[r["cell"]] = per_cell.get(r["cell"], 0) + 1
    assert all(v == 1 for v in per_cell.values()), per_cell


def test_fleet_lease_budget_exhaustion(tmp_path):
    """max_leases=1: the cell that kills its worker journals as
    crashed instead of looping forever."""
    marker = str(tmp_path / "die-once")
    cells = _noop_cells(1)
    cells[0]["params"]["die-once-marker"] = marker
    rep = dispatch.run_fleet(
        cells, dispatch.parse_workers("local"),
        campaign_id="exh", base_options=NOOP_OPTS, lease_s=120,
        max_leases=1, builder="jepsen_tpu.demo:demo_test")
    recs = store.latest_campaign_records("exh")
    assert recs[0]["outcome"] == "crashed"
    assert "lease budget exhausted" in recs[0]["error"]
    assert rep["summary"]["outcomes"] == {"crashed": 1}


def test_fleet_resume_skips_terminal_cells():
    cells = _noop_cells(2)
    dispatch.run_fleet(cells, dispatch.parse_workers("local"),
                       campaign_id="res", base_options=NOOP_OPTS,
                       lease_s=120, builder="jepsen_tpu.demo:demo_test")
    rep = dispatch.run_fleet(
        cells, dispatch.parse_workers("local"),
        campaign_id="res", resume=True, base_options=NOOP_OPTS,
        lease_s=120, builder="jepsen_tpu.demo:demo_test")
    assert rep["summary"]["skipped-resumed"] == 2
    # no new leases were granted on resume
    leases = [e for e in store.campaign_events("res")
              if e["event"] == "lease"]
    assert len(leases) == 2
    with pytest.raises(dispatch.FleetError):
        dispatch.run_fleet(cells, dispatch.parse_workers("local"),
                           campaign_id="res", base_options=NOOP_OPTS)


def test_fleet_dead_worker_probe_and_exhaustion():
    ws = dispatch.parse_workers("local,local")
    ws[1].probe = lambda timeout_s=30: "host unreachable"
    rep = dispatch.run_fleet(
        _noop_cells(2), ws, campaign_id="dead",
        base_options=NOOP_OPTS, lease_s=120,
        builder="jepsen_tpu.demo:demo_test")
    # the healthy worker carried the whole campaign
    assert rep["summary"]["outcomes"] == {"True": 2}
    assert any(e["event"] == "worker-dead" and e["worker"] == "local#2"
               for e in store.campaign_events("dead"))
    # ALL workers dead -> abort, resumable, not "passed"
    ws = dispatch.parse_workers("local")
    ws[0].probe = lambda timeout_s=30: "down"
    rep = dispatch.run_fleet(
        _noop_cells(1), ws, campaign_id="alldead",
        base_options=NOOP_OPTS, lease_s=120)
    assert rep["status"] == "aborted"
    assert rep["abort-reason"] == "workers-exhausted"


def test_fleet_pl014_errors_refuse_the_run():
    with pytest.raises(dispatch.FleetError):
        dispatch.run_fleet(_noop_cells(1), [],
                           campaign_id="nope", base_options=NOOP_OPTS)
    with pytest.raises(dispatch.FleetError):
        dispatch.run_fleet(_noop_cells(1),
                           dispatch.parse_workers("local"),
                           campaign_id="nope2", lease_s=0,
                           base_options=NOOP_OPTS)


def test_worker_parse_result():
    assert fworker.parse_result("") is None
    assert fworker.parse_result("noise\nJEPSEN-FLEET-RESULT: "
                                '{"outcome": true}') == {
        "outcome": True}
    # searched from the end; torn json -> None, not a crash
    assert fworker.parse_result("JEPSEN-FLEET-RESULT: {tor") is None
    # marker-shaped lines whose JSON isn't a record are NOT results
    assert fworker.parse_result("JEPSEN-FLEET-RESULT: [1, 2]") is None
    assert fworker.parse_result("JEPSEN-FLEET-RESULT: null") is None
    with pytest.raises(ValueError):
        fworker.resolve_builder("no-colon")


def test_worker_contains_builder_crash():
    rec = fworker.run_cell_spec({
        "cell": "x", "campaign": "c",
        "builder": "jepsen_tpu.demo:does_not_exist",
        "store-dir": store.base_dir})
    assert rec["outcome"] == "crashed"
    assert "does_not_exist" in rec["error"]


# ---------------------------------------------------------------------------
# service: /api logic without a socket

VALID_HIST = [
    {"type": "invoke", "process": 0, "f": "write", "value": 1},
    {"type": "ok", "process": 0, "f": "write", "value": 1},
    {"type": "invoke", "process": 1, "f": "read", "value": None},
    {"type": "ok", "process": 1, "f": "read", "value": 1},
]
BAD_HIST = [
    {"type": "invoke", "process": 0, "f": "write", "value": 1},
    {"type": "ok", "process": 0, "f": "write", "value": 1},
    {"type": "invoke", "process": 1, "f": "read", "value": None},
    {"type": "ok", "process": 1, "f": "read", "value": 99},
]


def test_api_check_matches_offline_checker():
    r = service.check_history({"history": VALID_HIST,
                               "model": "register", "engine": "wgl"})
    assert r["valid"] is True and r["engine"] == "wgl"
    r = service.check_history({"history": BAD_HIST,
                               "model": "register", "engine": "wgl"})
    assert r["valid"] is False
    # the linear engine agrees
    r = service.check_history({"history": BAD_HIST,
                               "model": "register",
                               "engine": "linear"})
    assert r["valid"] is False


def test_api_check_keyed_histories():
    hist = []
    for k, bad in (("a", False), ("b", True)):
        hist += [
            {"type": "invoke", "process": 0, "f": "write",
             "value": [k, 1]},
            {"type": "ok", "process": 0, "f": "write", "value": [k, 1]},
            {"type": "invoke", "process": 1, "f": "read",
             "value": [k, None]},
            {"type": "ok", "process": 1, "f": "read",
             "value": [k, 99 if bad else 1]},
        ]
    r = service.check_history({"history": hist, "model": "register",
                               "engine": "wgl", "keyed": True})
    assert r["valid"] is False
    assert r["keys"]["a"]["valid"] is True
    assert r["keys"]["b"]["valid"] is False


def test_api_check_rejections():
    with pytest.raises(service.ApiError) as e:
        service.check_history({"history": VALID_HIST,
                               "model": "no-such-model"})
    assert e.value.status == 400
    with pytest.raises(service.ApiError) as e:
        service.check_history({"history": VALID_HIST,
                               "engine": "warp"})
    assert e.value.status == 400
    with pytest.raises(service.ApiError) as e:
        service.check_history({"history": "nope"})
    assert e.value.status == 400
    # histlint catches the malformed history and names the code
    with pytest.raises(service.ApiError) as e:
        service.check_history({
            "history": [{"type": "ok", "process": 0, "f": "read"}],
            "model": "register"})
    assert e.value.status == 400
    assert any("HL" in d["code"]
               for d in e.value.payload["diagnostics"])
    with pytest.raises(service.ApiError) as e:
        service.check_history({"history": VALID_HIST,
                               "timeout-s": -1})
    assert e.value.status == 400


def test_api_check_bounds_history_size(monkeypatch):
    monkeypatch.setattr(service, "MAX_CHECK_OPS", 2)
    with pytest.raises(service.ApiError) as e:
        service.check_history({"history": VALID_HIST})
    assert e.value.status == 413


def test_api_campaign_submit_poll_and_shutdown():
    cid, meta = service.submit_campaign(
        {"axes": {"workload": ["noop"], "seed": [0, 1]},
         "options": {"time-limit": 1}, "parallel": 2, "id": "api1"})
    assert cid == "api1"
    assert meta["status-url"] == "/api/campaigns/api1"
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        st = service.campaign_status("api1")
        if st["status"] in ("complete", "aborted"):
            break
        time.sleep(0.2)
    assert st["status"] == "complete"
    assert st["outcomes"] == {"True": 2}
    with pytest.raises(service.ApiError) as e:
        service.submit_campaign({"axes": {"workload": ["noop"]},
                                 "id": "api1"})
    assert e.value.status == 409
    with pytest.raises(service.ApiError) as e:
        service.campaign_status("nope")
    assert e.value.status == 404
    with pytest.raises(service.ApiError) as e:
        service.submit_campaign({"axes": {}})
    assert e.value.status == 400


def test_api_campaign_id_path_traversal_refused():
    with pytest.raises(service.ApiError) as e:
        service.submit_campaign({"axes": {"workload": ["noop"]},
                                 "id": "../../../tmp/evil"})
    assert e.value.status == 400
    for cid in ("../x", "a/b", "..", ".hidden", ""):
        with pytest.raises(service.ApiError) as e:
            service.campaign_status(cid)
        assert e.value.status == 400
    # nothing escaped the store
    assert not os.path.exists(os.path.join(store.base_dir, "..",
                                           "campaigns"))


def test_api_campaign_protected_options_and_bad_ints():
    with pytest.raises(service.ApiError) as e:
        service.submit_campaign({"axes": {"workload": ["noop"]},
                                 "parallel": "two", "id": "badint"})
    assert e.value.status == 400
    with pytest.raises(service.ApiError) as e:
        service.submit_campaign({"axes": {"workload": ["noop"]},
                                 "device-slots": 0, "id": "badint2"})
    assert e.value.status == 400
    # a payload re-enabling real SSH / pointing at real hosts is
    # neutered: the campaign still runs on the dummy remote and
    # completes instead of dialing out
    cid, _meta = service.submit_campaign(
        {"axes": {"workload": ["noop"]},
         "options": {"ssh": {"dummy?": False},
                     "nodes": ["evil-host"], "time-limit": 1},
         "id": "neutered"})
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        st = service.campaign_status(cid)
        if st["status"] in ("complete", "aborted"):
            break
        time.sleep(0.2)
    assert st["status"] == "complete"
    assert st["outcomes"] == {"True": 1}


def test_api_check_whole_request_timeout_budget():
    r = service.check_history({"history": BAD_HIST,
                               "model": "register", "engine": "wgl",
                               "timeout-s": 1e-9})
    assert r["valid"] == "unknown"
    assert "budget exhausted" in r["error"]


def test_api_campaign_shutdown_aborts_gracefully():
    service.submit_campaign(
        {"axes": {"workload": ["noop"], "seed": list(range(50))},
         "options": {"time-limit": 30}, "id": "api-abort"})
    # let it actually start, then honor the shared latch
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if CampaignJournal("api-abort").load_meta():
            break
        time.sleep(0.1)
    service.shutdown(join_s=60)
    meta = CampaignJournal("api-abort").load_meta()
    assert meta["status"] == "aborted"
    assert service.latch().is_set()


# ---------------------------------------------------------------------------
# web handler: transport hardening over a real socket


@pytest.fixture()
def api_server():
    server = web.serve({"ip": "127.0.0.1", "port": 0})
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()


def _post(base, path, data, headers=None):
    req = urllib.request.Request(
        base + path, data=data,
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_web_api_check_roundtrip(api_server):
    s, r = _post(api_server, "/api/check",
                 json.dumps({"history": BAD_HIST, "model": "register",
                             "engine": "wgl"}).encode())
    assert s == 200 and r["valid"] is False


def test_web_api_oversized_body_gets_413_not_oom(api_server):
    """The regression test: an oversized declared body must be refused
    BEFORE any read. Only one byte is ever sent -- if the handler
    tried to read Content-Length bytes it would block and time out
    instead of answering 413 instantly."""
    s, r = _post(api_server, "/api/check", b"x",
                 headers={"Content-Length":
                          str(service.MAX_BODY_BYTES + 1)})
    assert s == 413
    assert "exceeds" in r["error"]


def test_web_api_json_errors(api_server):
    s, r = _post(api_server, "/api/nope", b"{}")
    assert s == 404 and "error" in r
    s, r = _post(api_server, "/api/check", b"{not json")
    assert s == 400 and "error" in r
    # GET on a POST-only route: 405, JSON
    try:
        urllib.request.urlopen(api_server + "/api/check", timeout=30)
        raise AssertionError("expected 405")
    except urllib.error.HTTPError as e:
        assert e.code == 405
        assert "error" in json.loads(e.read())
    # missing Content-Length: 411 (urllib always sends it, so go raw)
    import http.client
    host = api_server[len("http://"):]
    conn = http.client.HTTPConnection(host, timeout=30)
    conn.putrequest("POST", "/api/check", skip_accept_encoding=True)
    conn.endheaders()
    resp = conn.getresponse()
    assert resp.status == 411
    conn.close()
    # non-api POSTs stay plain HTML 404
    req = urllib.request.Request(api_server + "/files/x", data=b"{}")
    try:
        urllib.request.urlopen(req, timeout=30)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
        assert b"<h1>" in e.read()


def test_web_api_campaign_listing(api_server):
    CampaignJournal("listed").write_meta({"status": "complete"})
    with urllib.request.urlopen(api_server + "/api/campaigns",
                                timeout=30) as r:
        assert json.loads(r.read())["campaigns"] == ["listed"]
    with urllib.request.urlopen(
            api_server + "/api/campaigns/listed", timeout=30) as r:
        body = json.loads(r.read())
    assert body["status"] == "complete"


# ---------------------------------------------------------------------------
# backends: failover tiering


def test_failover_ladder_caching_and_floor():
    calls = []

    def fake_probe(tier, timeout_s=None):
        calls.append(tier)
        return None if tier == "gpu" else "down"

    f = fbackends.Failover(ladder=("tpu", "gpu", "cpu"),
                           probe_fn=fake_probe)
    assert f.choose() == "gpu"
    assert f.choose() == "gpu"
    assert calls == ["tpu", "gpu"]      # cached: one probe per tier
    down = fbackends.Failover(
        probe_fn=lambda t, timeout_s=None: "down")
    assert down.choose() == "cpu"       # the unconditional floor
    with pytest.raises(ValueError):
        fbackends.Failover(ladder=("warp",))
    with pytest.raises(ValueError):
        fbackends.Failover(ladder=())
    assert fbackends.as_failover("gpu,cpu").ladder == ["gpu", "cpu"]
    assert fbackends.as_failover(f) is f
    assert fbackends.as_failover(True).ladder == list(
        fbackends.DEFAULT_LADDER)


def test_backend_apply_degrades_linearizable_gates():
    from jepsen_tpu import checker as cc
    from jepsen_tpu.checker import checkers as cks
    from jepsen_tpu.models import register_spec
    lin = cks.Linearizable(register_spec, "jax-wgl")
    test = {"checker": cc.compose({"w": lin, "stats": cks.stats()})}
    fbackends.apply(test, "cpu")
    assert lin.algorithm == "linear"
    assert test["backend"] == "cpu"
    # a healthy tier leaves the checker's own choice alone
    lin2 = cks.Linearizable(register_spec, "jax-wgl")
    fbackends.apply({"checker": lin2}, "tpu")
    assert lin2.algorithm == "jax-wgl"
    assert fbackends.tier_env("cpu") == {"JAX_PLATFORMS": "cpu"}


def test_cpu_probe_is_healthy_here():
    assert fbackends.probe("cpu") is None


def test_scheduler_applies_backend_tier():
    from jepsen_tpu import checker as cc
    from jepsen_tpu import client as jc
    from jepsen_tpu import generator as gen
    from jepsen_tpu import tests as tst
    from jepsen_tpu.checker import checkers as cks
    from jepsen_tpu.models import register_spec

    class OkClient(jc.Client):
        def open(self, test, node):
            return self

        def invoke(self, test, op):
            return dict(op, type="ok")

    lin = cks.Linearizable(register_spec, "jax-wgl")
    t = tst.noop_test()
    t.update({"ssh": {"dummy?": True}, "obs?": False, "name": "bk",
              "nodes": ["n1"], "concurrency": 1, "client": OkClient(),
              "checker": lin,
              "generator": gen.clients(gen.limit(
                  3, gen.repeat({"f": "read"})))})
    f = fbackends.Failover(ladder=("tpu", "cpu"),
                           probe_fn=lambda t_, timeout_s=None: "down")
    rep = scheduler.run_cells([{"id": "a", "test": t}],
                              campaign_id="bk", backends=f)
    rec = store.latest_campaign_records("bk")[0]
    assert rec["backend"] == "cpu"
    assert lin.algorithm == "linear"    # the gate really was degraded
    assert rep["summary"]["outcomes"] == {"True": 1}
