"""jaxlint tests: clean model steps lint clean; each hazard
class -- captured constants, weak-typed scalars, host callbacks,
untraceable steps, int32 index-width overflow -- is caught with its
specific code."""

import numpy as np

import jax
import jax.numpy as jnp

from jepsen_tpu.analysis import jaxlint
from jepsen_tpu.models import base as mbase
import jepsen_tpu.models.registers  # noqa: F401 - registers specs
import jepsen_tpu.models.mutex  # noqa: F401
import jepsen_tpu.models.queues  # noqa: F401


def codes(diags):
    return [d.code for d in diags]


def errors(diags):
    return [d for d in diags if d.severity == "error"]


# ---------------------------------------------------------------------------
# the shipped model specs are hazard-free

def test_shipped_model_steps_lint_clean():
    for name in ("register", "cas-register", "mutex", "fifo-queue",
                 "unordered-queue"):
        spec = mbase.model_spec(name)
        diags = jaxlint.lint_model_spec(spec)
        assert errors(diags) == [], (name, codes(diags))


# ---------------------------------------------------------------------------
# seeded hazards

def test_captured_constant_flags_jx002():
    baked = np.arange(5000, dtype=np.int32)

    def step(x):
        return x + jnp.asarray(baked)

    diags, _ = jaxlint.lint_fn(step, jnp.zeros(5000, jnp.int32))
    assert "JX002" in codes(diags)


def test_weak_typed_input_flags_jx001():
    def f(x, bound):
        return x + bound

    # a Python int argument traces as a weak-typed scalar
    diags, _ = jaxlint.lint_fn(f, jnp.zeros((4,), jnp.int32), 3)
    assert "JX001" in codes(diags)
    # an explicit dtype does not
    diags2, _ = jaxlint.lint_fn(f, jnp.zeros((4,), jnp.int32),
                                jnp.int32(3))
    assert "JX001" not in codes(diags2)


def test_host_callback_flags_jx003():
    def step(x):
        jax.debug.print("x={x}", x=x)
        return x * 2

    diags, _ = jaxlint.lint_fn(step, jnp.zeros((4,), jnp.int32))
    assert "JX003" in codes(diags)
    assert errors(diags)


def test_untraceable_step_reported_not_raised():
    def step(x):
        if x[0] > 0:           # Python control flow on a traced value
            return x
        return -x

    diags, closed = jaxlint.lint_fn(step, jnp.zeros((4,), jnp.int32))
    assert closed is None
    assert codes(diags) == ["JX000"]
    assert "trace" in diags[0].message


def test_wide_dtype_flags_jx006():
    def step(x):
        return x.astype(jnp.int64).sum()

    # x64 is disabled by default: int64 silently becomes int32, so
    # force-enable inside the test only
    with jax.experimental.enable_x64():
        diags, _ = jaxlint.lint_fn(step, jnp.zeros((4,), jnp.int32))
    assert "JX006" in codes(diags)


# ---------------------------------------------------------------------------
# int32 index-width conformance

def test_history_size_limits():
    assert jaxlint.lint_history_size(10_000) == []
    big = jaxlint.lint_history_size(2**28, arg_width=1)
    assert codes(big) == ["JX005"]          # within 2x of the ceiling
    over = jaxlint.lint_history_size(2**30, arg_width=1)
    assert codes(over) == ["JX004"]
    assert errors(over)
    # the key axis multiplies cell count
    keyed = jaxlint.lint_history_size(2**22, arg_width=1, keys=256)
    assert codes(keyed) == ["JX004"]


def test_search_plan_clean_at_tier1_scales():
    spec = mbase.model_spec("cas-register")
    assert jaxlint.lint_search_plan(
        4096, S=2, arg_width=spec.arg_width) == []
