"""Coordinator HA tests (fleet/ha.py + the FL016 chain audit + PL024):
the coordinator role as a leased, failover-able identity in the
journal. Covers the epoch fold, fence races (double-standby), zombie
fencing at the lease/renewal layer, skew-immune standby detection,
chaos coordinator-kill determinism, the torn-rewrite fsync regression,
the scheduler's HA-resume refusal, and THE acceptance run: SIGKILL the
live coordinator mid-campaign and let a standby fence it, resume, and
finish with exactly one terminal per cell and a clean audit."""

import datetime
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from jepsen_tpu import store
from jepsen_tpu.analysis import fleetlint, planlint
from jepsen_tpu.analysis.diagnostics import ERROR, WARNING
from jepsen_tpu.campaign import compile_cache, plan, scheduler
from jepsen_tpu.campaign.journal import CampaignJournal
from jepsen_tpu.fleet import chaos as fchaos
from jepsen_tpu.fleet import dispatch, ha


@pytest.fixture(autouse=True)
def store_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))
    compile_cache.reset()
    yield
    compile_cache.reset()


def _codes(diags):
    return [d.code for d in diags]


def _error_codes(diags):
    return [d.code for d in diags if d.severity == ERROR]


def _stamp(offset_s=0.0):
    """A journal ``t`` stamp offset from now (negative = past)."""
    return store.local_time(datetime.datetime.now().astimezone()
                            + datetime.timedelta(seconds=offset_s))


def mk_ha(cid, status="running", **extra):
    jr = CampaignJournal(cid)
    jr.write_meta({"status": status, "mode": "fleet",
                   "cells": ["a", "b"], "workers": ["w1"],
                   "lease-s": 60.0, "max-leases": 3,
                   "coordinator-lease-s": 5.0, **extra})
    return jr


def lease(jr, epoch, writer=None, t=None, lease_s=5.0):
    rec = {"event": ha.LEASE_EVENT, "epoch": epoch,
           "lease-s": lease_s, "t": t or store.local_time()}
    if writer is not None:
        rec["writer"] = writer
    jr.append_event(rec)


# ---------------------------------------------------------------------------
# the epoch fold + fence races


def test_coordinator_state_fold_is_monotone_and_first_fence_wins():
    recs = [
        {"event": "coordinator-lease", "epoch": 1, "writer": "a:1"},
        {"event": "coordinator-lease", "epoch": 1, "writer": "a:1"},
        # first takeover claiming prev-epoch 1 wins...
        {"event": "coordinator-takeover", "epoch": 2, "prev-epoch": 1,
         "writer": "b:2"},
        # ...a second claim of the SAME predecessor is a losing race
        {"event": "coordinator-takeover", "epoch": 3, "prev-epoch": 1,
         "writer": "c:3"},
        # a zombie re-claim of an old epoch changes nothing
        {"event": "coordinator-lease", "epoch": 1, "writer": "a:1"},
    ]
    assert ha.coordinator_state(recs) == (2, "b:2")
    assert ha.current_epoch(recs) == 2
    assert ha.current_epoch([]) == 0
    assert ha.coordinator_state(None) == (0, None)
    # non-HA journals fold to (0, None)
    assert ha.coordinator_state([{"cell": "a", "outcome": True}]) \
        == (0, None)


def test_fence_appends_takeover_and_detects_a_lost_race():
    jr = mk_ha("fence")
    lease(jr, 1, t=_stamp(-60))
    won = ha.fence(jr)
    assert won == 2
    rec = [r for r in jr.records()
           if r.get("event") == ha.TAKEOVER_EVENT][0]
    assert rec["prev-epoch"] == 1
    assert rec["prev-writer"] == jr.writer
    assert rec["prev-lease-t"] and rec["lease-s"] == 5.0
    # the compare-and-swap guard: we judged epoch 1 expired, but a
    # rival's takeover landed first -- fencing now would fence the
    # NEW, live coordinator, so the fence must stand down
    jr2 = mk_ha("fence2")
    lease(jr2, 1, writer="coord:1", t=_stamp(-60))
    jr2.append_event({"event": ha.TAKEOVER_EVENT, "epoch": 2,
                      "prev-epoch": 1, "prev-writer": "coord:1",
                      "writer": "rival:9", "t": store.local_time()})
    assert ha.fence(jr2, expect_epoch=1) is None
    assert ha.current_epoch(jr2.records()) == 2   # nothing appended


def test_double_standby_race_exactly_one_fence_wins():
    jr = mk_ha("race")
    lease(jr, 1, writer="coord:1", t=_stamp(-120))
    a = ha.Standby("race", lease_s=0.1, grace_s=0.05, poll_s=0.01)
    b = ha.Standby("race", lease_s=0.1, grace_s=0.05, poll_s=0.01)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline \
            and not (a.poll() == "expired" and b.poll() == "expired"):
        time.sleep(0.02)
    assert a.poll() == "expired" and b.poll() == "expired"
    results = {}
    barrier = threading.Barrier(2)

    def racer(name, sb):
        barrier.wait()
        results[name] = sb.fence()

    ts = [threading.Thread(target=racer, args=("a", a)),
          threading.Thread(target=racer, args=("b", b))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10)
    wins = [e for e in results.values() if e is not None]
    # the journal serialized the race: exactly one standby won, and
    # the fold agrees with the winner. The loser either appended a
    # losing takeover record (read before the winner's append landed)
    # or abandoned pre-append on the compare-and-swap guard (read
    # after) -- both stand-downs are legal
    assert len(wins) == 1 and wins[0] == 2
    assert ha.current_epoch(jr.records()) == 2
    from jepsen_tpu.analysis.fleetmodel import CampaignModel
    diags, audited = fleetlint._ha_diags(CampaignModel("race"))
    assert audited in (1, 2)
    assert not [d for d in diags if "zombie" in d.message
                or "split brain" in d.message]


# ---------------------------------------------------------------------------
# the active side: renewals and zombie fencing


def test_coordinator_lease_renews_then_refuses_once_fenced():
    jr = mk_ha("active")
    jr.epoch = 1
    fenced_with = []
    ctl = ha.CoordinatorLease(jr, lease_s=5.0, epoch=1,
                              on_fenced=fenced_with.append)
    assert ctl.renew() is True
    recs = jr.records()
    grant = [r for r in recs if r.get("event") == ha.LEASE_EVENT][-1]
    assert grant["epoch"] == 1 and grant["lease-s"] == 5.0
    assert grant["writer"] == jr.writer
    assert ctl.fenced() is False
    # a standby fences us behind our back...
    jr.append_event({"event": ha.TAKEOVER_EVENT, "epoch": 2,
                     "prev-epoch": 1, "prev-writer": jr.writer,
                     "writer": "standby:7", "t": store.local_time()})
    # ...the cached flag is still stale, the refresh path is not
    assert ctl.fenced() is False
    assert ctl.fenced(refresh=True) is True
    assert ctl.fenced_by == (2, "standby:7")
    assert fenced_with == [(2, "standby:7")]
    # a fenced coordinator never appends another renewal
    n = len(jr.records())
    assert ctl.renew() is False
    assert len(jr.records()) == n
    # and on_fenced fired exactly once even if re-checked
    assert ctl.fenced(refresh=True) is True
    assert fenced_with == [(2, "standby:7")]


def test_same_epoch_claimed_first_by_a_foreign_writer_fences_us():
    """The fold is first-claim-wins per epoch: if someone else already
    holds the epoch we think is ours (a lost resume race), our very
    first renewal must refuse and flag us fenced."""
    jr = mk_ha("usurp")
    jr.epoch = 1
    lease(jr, 1, writer="other:2")     # they claimed epoch 1 first
    ctl = ha.CoordinatorLease(jr, lease_s=5.0, epoch=1)
    assert ctl.renew() is False
    assert ctl.fenced() is True
    assert ctl.fenced_by == (1, "other:2")
    # ...and our refusal appended nothing
    assert all(r.get("writer") == "other:2" for r in jr.records()
               if r.get("event") == ha.LEASE_EVENT)


# ---------------------------------------------------------------------------
# the passive side: skew-immune detection


def test_standby_never_fences_while_the_journal_grows():
    """A live coordinator with an hours-BEHIND wall clock writes
    stale-looking stamps forever; arrivals must protect it."""
    jr = mk_ha("behind")
    sb = ha.Standby("behind", lease_s=0.2, grace_s=0.1, poll_s=0.01)
    for _ in range(4):
        lease(jr, 1, t=_stamp(-3600), lease_s=0.2)
        assert sb.poll() is None
        time.sleep(0.12)
    # the journal kept growing inside every lease window: no expiry
    assert sb.poll() is None


def test_standby_detects_a_dead_coordinator_with_an_ahead_clock():
    """A dead coordinator whose stamps run far AHEAD of the standby's
    clock: the observed future-skew bound credits the offset so the
    stamp condition cannot mask the death forever."""
    jr = mk_ha("ahead")
    lease(jr, 1, t=_stamp(+3600), lease_s=0.2)
    sb = ha.Standby("ahead", lease_s=0.2, grace_s=0.1, poll_s=0.01)
    assert sb.poll() is None          # first sight: journal "moved"
    deadline = time.monotonic() + 10
    status = None
    while time.monotonic() < deadline:
        status = sb.poll()
        if status == "expired":
            break
        time.sleep(0.05)
    assert status == "expired"
    # the fence records the skew allowance it credited
    assert sb.fence() == 2
    rec = [r for r in jr.records()
           if r.get("event") == ha.TAKEOVER_EVENT][0]
    assert rec["skew-allowance-s"] > 3000


def test_standby_wait_returns_complete_for_a_finalized_campaign():
    mk_ha("done", status="complete")
    sb = ha.Standby("done", lease_s=0.2, grace_s=0.1, poll_s=0.01)
    assert sb.wait(timeout_s=5) == ("complete", None)


def test_standby_wait_times_out_on_a_non_ha_journal():
    """HA off: no coordinator-lease records, never fenced."""
    jr = mk_ha("noha")
    jr.append_event({"event": "lease", "cell": "a", "worker": "w1",
                     "attempt": 1, "lease-s": 60.0,
                     "t": _stamp(-3600)})
    sb = ha.Standby("noha", lease_s=0.1, grace_s=0.05, poll_s=0.01)
    assert sb.wait(timeout_s=1.0) == ("timeout", None)


# ---------------------------------------------------------------------------
# chaos: the coordinator-kill fault


def test_chaos_coordinator_kill_parse_and_deterministic_plan():
    prof = fchaos.parse("coordinator-kill:7")
    assert prof.coordinator_kill == 1
    assert prof.seed == 7
    ids = [f"c{i}" for i in range(6)]
    pick = prof.plan_coordinator_kill(ids)
    assert pick == prof.plan_coordinator_kill(list(reversed(ids)))
    assert pick in ids
    # mid-campaign: the first (sorted) cell is skipped given a choice
    assert pick != sorted(ids)[0]
    # a one-cell campaign still kills (on the only cell there is)
    assert prof.plan_coordinator_kill(["solo"]) == "solo"
    # no-kill profiles plan nothing
    assert fchaos.parse("flaky-exec:1").plan_coordinator_kill(ids) \
        is None
    assert prof.with_seed(8).plan_coordinator_kill(ids) \
        == prof.with_seed(8).plan_coordinator_kill(ids)


# ---------------------------------------------------------------------------
# FL016: golden journals


def _ha_fleet(cid, status="complete"):
    jr = CampaignJournal(cid)
    jr.write_meta({"status": status, "mode": "fleet", "cells": ["a"],
                   "workers": ["w1"], "lease-s": 60.0, "max-leases": 3,
                   "coordinator-lease-s": 5.0, "ha-epoch": 1})
    return jr


def _cell(jr, cell="a", epoch=1, writer=None, **kw):
    rec = {"cell": cell, "group": cell, "params": {}, "outcome": True,
           "valid": True, "worker": "w1", "attempt": 1, "epoch": epoch,
           **kw}
    if writer is not None:
        rec["writer"] = writer
    jr.append_event({"event": "lease", "cell": cell, "worker": "w1",
                     "attempt": 1, "lease-s": 60.0, "epoch": epoch,
                     "t": store.local_time(),
                     **({"writer": writer} if writer else {})})
    jr.append_cell(rec)


def test_fl016_clean_takeover_chain_passes():
    jr = _ha_fleet("golden")
    lease(jr, 1, writer="coord:1", t=_stamp(-60))
    jr.append_event({"event": ha.TAKEOVER_EVENT, "epoch": 2,
                     "prev-epoch": 1, "prev-writer": "coord:1",
                     "reason": "lease-expired", "t": store.local_time(),
                     "prev-lease-t": _stamp(-60), "lease-s": 5.0})
    lease(jr, 2)
    _cell(jr, "a", epoch=2)
    diags = fleetlint.lint_campaign("golden")
    assert "FL016" not in _codes(diags)


def test_fl016_zombie_append_after_the_fence():
    jr = _ha_fleet("zombie")
    lease(jr, 1, writer="coord:1", t=_stamp(-60))
    jr.append_event({"event": ha.TAKEOVER_EVENT, "epoch": 2,
                     "prev-epoch": 1, "prev-writer": "coord:1",
                     "reason": "lease-expired", "t": store.local_time(),
                     "prev-lease-t": _stamp(-60), "lease-s": 5.0})
    lease(jr, 2)
    # the fenced coordinator's late append slips through the race
    # window: stamped with the PRE-takeover epoch
    _cell(jr, "a", epoch=1, writer="coord:1")
    diags = fleetlint.lint_campaign("zombie")
    zombie = [d for d in diags if d.code == "FL016"
              and "zombie append" in d.message]
    assert zombie and zombie[0].severity == ERROR


def test_fl016_zombie_renewal_and_split_brain():
    jr = _ha_fleet("renew")
    lease(jr, 1, writer="coord:1", t=_stamp(-60))
    jr.append_event({"event": ha.TAKEOVER_EVENT, "epoch": 2,
                     "prev-epoch": 1, "prev-writer": "coord:1",
                     "reason": "lease-expired", "t": store.local_time(),
                     "prev-lease-t": _stamp(-60), "lease-s": 5.0})
    lease(jr, 2)
    lease(jr, 1, writer="coord:1")          # zombie renewal
    lease(jr, 2, writer="intruder:3")       # split brain on epoch 2
    msgs = [d.message for d in fleetlint.lint_campaign("renew")
            if d.code == "FL016" and d.severity == ERROR]
    assert any("zombie coordinator renewal" in m for m in msgs)
    assert any("split brain" in m for m in msgs)


def test_fl016_premature_takeover_and_self_fence():
    jr = _ha_fleet("premature")
    lease(jr, 1, writer="coord:1", t=_stamp(-1))   # renewed 1s ago
    jr.append_event({"event": ha.TAKEOVER_EVENT, "epoch": 2,
                     "prev-epoch": 1, "prev-writer": "coord:1",
                     "reason": "lease-expired", "t": store.local_time(),
                     "prev-lease-t": _stamp(-1), "lease-s": 5.0,
                     "writer": "coord:1"})
    lease(jr, 2, writer="coord:1")
    _cell(jr, "a", epoch=2, writer="coord:1")
    msgs = [d.message for d in fleetlint.lint_campaign("premature")
            if d.code == "FL016" and d.severity == ERROR]
    assert any("premature takeover" in m for m in msgs)
    assert any("names ITSELF" in m for m in msgs)


def test_fl016_forced_takeover_skips_the_expiry_requirement():
    jr = _ha_fleet("forced")
    lease(jr, 1, writer="coord:1", t=_stamp(-1))
    jr.append_event({"event": ha.TAKEOVER_EVENT, "epoch": 2,
                     "prev-epoch": 1, "prev-writer": "coord:1",
                     "reason": "manual-resume", "forced": True,
                     "t": store.local_time()})
    lease(jr, 2)
    _cell(jr, "a", epoch=2)
    assert not [d for d in fleetlint.lint_campaign("forced")
                if d.code == "FL016"]


def test_fl016_vanished_coordinator_kill_warns():
    """Chaos scheduled a coordinator-kill but the journal carries no
    HA events at all: the kill (or the protocol) vanished."""
    jr = CampaignJournal("vanish")
    jr.write_meta({"status": "complete", "mode": "fleet",
                   "cells": ["a"], "workers": ["w1"], "lease-s": 60.0,
                   "max-leases": 3,
                   "chaos": fchaos.parse("coordinator-kill:7")
                   .describe()})
    _cell(jr, "a", epoch=None)
    diags = [d for d in fleetlint.lint_campaign("vanish")
             if d.code == "FL016"]
    assert diags and diags[0].severity == WARNING
    assert "vanished" in diags[0].message


# ---------------------------------------------------------------------------
# PL024: the HA knobs


def test_pl024_accepts_a_sane_ha_config():
    assert planlint.lint_ha({"ha?": True, "coordinator-lease-s": 15,
                             "takeover-grace-s": 5,
                             "renew-interval-s": 5,
                             "lease-s": 300}) == []
    assert planlint.lint_ha({"ha?": False}) == []
    assert planlint.lint_ha({}) == []


def test_pl024_rejects_bad_knob_values():
    for v in (0, -1, "3", True):
        diags = planlint.lint_ha({"ha?": True,
                                  "coordinator-lease-s": v})
        assert any(d.code == "PL024" and d.severity == ERROR
                   and d.location == "ha.coordinator-lease-s"
                   for d in diags), v
    diags = planlint.lint_ha({"ha?": True, "coordinator-lease-s": 10,
                              "takeover-grace-s": -2})
    assert any(d.location == "ha.takeover-grace-s" for d in diags)


def test_pl024_self_fencing_renew_interval():
    diags = planlint.lint_ha({"ha?": True, "coordinator-lease-s": 5,
                              "renew-interval-s": 5})
    assert any(d.code == "PL024" and d.severity == ERROR
               and "renew" in d.message for d in diags)


def test_pl024_standby_needs_a_reachable_store():
    diags = planlint.lint_ha({"ha?": True, "standby?": True,
                              "store-reachable?": False})
    assert any(d.code == "PL024" and d.severity == ERROR
               for d in diags)
    assert planlint.lint_ha({"ha?": True, "coordinator-lease-s": 5,
                             "standby?": True,
                             "store-reachable?": True}) == []


def test_pl024_coordinator_kill_without_ha_is_unfenceable():
    diags = planlint.lint_ha({"ha?": False,
                              "chaos-coordinator-kill?": True})
    assert any(d.code == "PL024" and d.severity == ERROR
               for d in diags)
    assert planlint.lint_ha({"ha?": True, "coordinator-lease-s": 5,
                             "chaos-coordinator-kill?": True}) == []


def test_pl024_warns_when_coordinator_ttl_exceeds_cell_lease():
    diags = planlint.lint_ha({"ha?": True, "coordinator-lease-s": 600,
                              "lease-s": 60})
    assert any(d.code == "PL024" and d.severity == WARNING
               for d in diags)


# ---------------------------------------------------------------------------
# satellite: torn-rewrite regression (fsync before rename) + the
# scheduler's HA-resume refusal


def test_campaign_meta_rewrite_fsyncs_before_rename(monkeypatch):
    """campaign.json is rewritten in place on every status change: the
    temp file's data blocks must hit disk BEFORE os.replace publishes
    the name, or a power cut can publish a stale-but-valid meta."""
    calls = []
    real_fsync, real_replace = os.fsync, os.replace

    def spy_fsync(fd):
        calls.append(("fsync",))
        return real_fsync(fd)

    def spy_replace(src, dst):
        calls.append(("replace", os.path.basename(dst)))
        return real_replace(src, dst)

    monkeypatch.setattr(os, "fsync", spy_fsync)
    monkeypatch.setattr(os, "replace", spy_replace)
    jr = CampaignJournal("torn")
    jr.write_meta({"status": "running", "mode": "fleet"})
    upto = {i for i, c in enumerate(calls)
            if c == ("replace", "campaign.json")}
    assert upto, calls
    # at least one fsync strictly precedes the publishing rename
    assert any(("fsync",) in calls[:i] for i in upto), calls
    # and the rewrite really is atomic: no torn half-file on disk
    meta = json.load(open(store.campaign_path("torn", "campaign.json")))
    assert meta["status"] == "running"


def test_scheduler_refuses_to_resume_an_ha_journal():
    jr = mk_ha("hares")
    lease(jr, 1)
    cells = plan.expand({"axes": {"workload": ["noop"], "seed": [0]}})
    with pytest.raises(scheduler.CampaignError, match="coordinator-HA"):
        scheduler.run_cells(cells, campaign_id="hares", resume=True)


def test_scheduler_resume_preserves_prior_meta_keys():
    """A resume's meta rewrite must not strip keys a prior (possibly
    newer) coordinator recorded alongside the scheduler's own."""
    from jepsen_tpu import checker as cc
    from jepsen_tpu import client as jc
    from jepsen_tpu import generator as gen
    from jepsen_tpu import tests as tst

    class OkClient(jc.Client):
        def open(self, test, node):
            return self

        def invoke(self, test, op):
            return dict(op, type="ok")

    t = tst.noop_test()
    t.update(ssh={"dummy?": True}, name="keep-cell", nodes=["n1"],
             concurrency=1, client=OkClient(), checker=cc.noop(),
             generator=gen.clients(
                 gen.limit(2, gen.repeat({"f": "read"}))))
    t["obs?"] = False
    cells = [{"id": "a", "test": t}]
    scheduler.run_cells(cells, campaign_id="keep", fleetlint=False,
                        certify=False, ledger=False)
    jr = CampaignJournal("keep")
    meta = jr.load_meta()
    meta["extra-key"] = "survives"
    jr.write_meta(meta)
    rep = scheduler.run_cells(cells, campaign_id="keep", resume=True,
                              fleetlint=False, certify=False,
                              ledger=False)
    assert rep["status"] == "complete"
    meta = jr.load_meta()
    assert meta["status"] == "complete"
    assert meta["extra-key"] == "survives"
    assert meta["resumes"] == 1


# ---------------------------------------------------------------------------
# THE acceptance run: kill the coordinator, let a standby finish


NOOP_OPTS = {"nodes": ["n1"], "concurrency": 1, "ssh": {"dummy?": True},
             "time-limit": 1, "workload": "noop"}

_COORD_SCRIPT = """
import sys
from jepsen_tpu import store
store.base_dir = sys.argv[1]
from jepsen_tpu.campaign import plan
from jepsen_tpu.fleet import chaos, dispatch
cells = plan.expand({"axes": {"workload": ["noop"], "seed": [0, 1]}})
dispatch.run_fleet(
    cells, dispatch.parse_workers("local,local"),
    campaign_id="ha-kill", builder="jepsen_tpu.demo:demo_test",
    base_options=%r, lease_s=300, max_leases=5,
    coordinator_lease_s=1.0, takeover_grace_s=0.5,
    chaos=chaos.parse("coordinator-kill:7"))
""" % (NOOP_OPTS,)


def test_ha_takeover_e2e_coordinator_kill_standby_finishes(tmp_path):
    """SIGKILL the live coordinator right after a seeded lease-grant
    append; a standby detects the dead lease, fences it with a
    journaled takeover, resumes the campaign, and finishes with
    exactly one terminal record per cell and a ZERO-error,
    ZERO-warning fleetlint audit (FL004/FL007/FL016)."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.pathsep.join(
               [os.path.dirname(os.path.dirname(os.path.abspath(
                   __file__)))] + sys.path)}
    proc = subprocess.run(
        [sys.executable, "-c", _COORD_SCRIPT, store.base_dir],
        capture_output=True, text=True, timeout=300, env=env)
    # the chaos fault really SIGKILLed the coordinator mid-campaign
    assert proc.returncode == -signal.SIGKILL, \
        (proc.returncode, proc.stdout[-2000:], proc.stderr[-2000:])
    assert os.path.exists(ha.takeover_marker("ha-kill"))
    recs = store.load_campaign_records("ha-kill")
    assert ha.current_epoch(recs) == 1
    meta = CampaignJournal("ha-kill").load_meta()
    assert meta["status"] == "running"          # died mid-flight
    assert meta["coordinator-lease-s"] == 1.0
    assert meta["ha-epoch"] == 1

    # the standby tails, detects expiry, fences
    sb = ha.Standby("ha-kill", poll_s=0.05)
    status, epoch = sb.wait(timeout_s=120)
    assert (status, epoch) == ("takeover", 2)
    # ...and resumes through the fleet path under the won epoch
    rep = dispatch.run_fleet(
        plan.expand({"axes": {"workload": ["noop"], "seed": [0, 1]}}),
        dispatch.parse_workers("local,local"),
        campaign_id="ha-kill", resume=True, ha_epoch=epoch,
        builder="jepsen_tpu.demo:demo_test", base_options=NOOP_OPTS,
        lease_s=300, max_leases=5,
        coordinator_lease_s=1.0, takeover_grace_s=0.5)
    assert rep["status"] == "complete"

    recs = store.load_campaign_records("ha-kill")
    terminal = {}
    for r in recs:
        if not r.get("event"):
            terminal[r["cell"]] = terminal.get(r["cell"], 0) + 1
    assert terminal == {"noop seed=0": 1, "noop seed=1": 1} \
        or (len(terminal) == 2 and set(terminal.values()) == {1})
    # exactly one takeover, naming the dead epoch under a new writer
    takeovers = [r for r in recs if r.get("event") == ha.TAKEOVER_EVENT]
    assert len(takeovers) == 1
    assert takeovers[0]["epoch"] == 2
    assert takeovers[0]["prev-epoch"] == 1
    assert takeovers[0]["writer"] != takeovers[0]["prev-writer"]
    # every post-takeover record is epoch-2 stamped: no zombies
    seen_takeover = False
    for r in recs:
        if r.get("event") == ha.TAKEOVER_EVENT:
            seen_takeover = True
        elif seen_takeover and r.get("epoch") is not None:
            assert r["epoch"] == 2, r
    # the audit is the oracle: zero errors AND zero warnings
    fa = rep["fleet_analysis"]
    assert fa["counts"]["error"] == 0, fa
    assert fa["counts"]["warning"] == 0, fa
    assert fa["checks"]["ha_takeovers_audited"] == 1, fa
    assert fleetlint.load_report("ha-kill")["counts"] == fa["counts"]
