"""Device-level search introspection: progress-tensor heartbeats off
the device host loops (explored / frontier / depth monotone on a live
scrape), padding / duty-cycle accounting per n-bucket, the run-scoped
sink fix that stops concurrent campaign cells folding their heartbeat
counters into one series, the --profile XLA capture (and its
containment when the profiler is unavailable), the service SLO
histograms on /api/check, the campaign metrics fold, planlint PL019,
and the trace-summary waste table."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from jepsen_tpu import obs, store, web
from jepsen_tpu.analysis import planlint
from jepsen_tpu.checker import jax_wgl
from jepsen_tpu.fleet import service
from jepsen_tpu.models import cas_register_spec
from jepsen_tpu.obs import merge as obs_merge
from jepsen_tpu.obs import profile as obs_profile
from jepsen_tpu.obs import search as obs_search
from jepsen_tpu.parallel import keyshard
from jepsen_tpu.simulate import random_history

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def store_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))
    service.reset()
    yield
    service.reset()


def _hist(n_ops=400, n_procs=8, seed=7):
    import random as _r
    return random_history(_r.Random(seed), "cas-register",
                          n_procs=n_procs, n_ops=n_ops, crash_p=0.02)


# ---------------------------------------------------------------------------
# progress-tensor heartbeats

def test_single_key_heartbeat_carries_progress_tensor():
    """A single-key device search's heartbeats carry frontier,
    cumulative explored, AND the deepest linearized-ok depth, and the
    duty-cycle accounting (device_busy_s, padding per bucket) lands in
    the registry."""
    e, st = cas_register_spec.encode(_hist())
    tr, reg = obs.Tracer(), obs.Registry()
    with obs.bind(tr, reg):
        r = jax_wgl.check_encoded(cas_register_spec, e, st,
                                  chunk_iters=4)
    assert r["valid"] in (True, False)
    hb = [ev for ev in tr.events()
          if ev.get("name") == "wgl.heartbeat.jax-wgl"]
    assert hb, "no heartbeats for a multi-chunk search"
    args = hb[-1]["args"]
    assert {"iteration", "frontier", "explored", "depth",
            "chunk_s"} <= set(args)
    assert args["depth"] >= 0
    # per-dispatch depth is monotone (best_depth only grows)
    depths = [h["args"]["depth"] for h in hb]
    assert depths == sorted(depths)
    assert reg.counter_value("wgl.device_busy_s",
                             engine="jax-wgl") > 0
    assert reg.gauge_value("wgl.search_depth", engine="jax-wgl") \
        == depths[-1]
    # padding accounting: real rows vs the padded power-of-two bucket
    snap = reg.snapshot()["counters"]
    real = [v for k, v in snap.items()
            if k.startswith("wgl.cells_real{")]
    padded = [v for k, v in snap.items()
              if k.startswith("wgl.cells_padded{")]
    assert real == [len(e)]
    assert padded and padded[0] >= 0
    plan_ev = [ev for ev in tr.events()
               if ev.get("name") == "wgl.plan.jax-wgl"]
    assert plan_ev and plan_ev[0]["args"]["rows_real"] == len(e)


def test_batch_heartbeat_explored_and_depth_ride_one_device_get():
    """The key batch's heartbeats now carry summed explored + max
    depth (fetched on the same single device_get as status/top), and
    the batch's padding accounting counts K * n_pad rows against the
    live keys' real ops."""
    pairs = [cas_register_spec.encode(_hist(200, 4, seed=s))
             for s in (1, 2, 3)]
    tr, reg = obs.Tracer(), obs.Registry()
    with obs.bind(tr, reg):
        rs = keyshard.check_batch_encoded(cas_register_spec, pairs,
                                          chunk_iters=4)
    assert all(r["valid"] in (True, False) for r in rs)
    hb = [ev for ev in tr.events()
          if ev.get("name") == "wgl.heartbeat.jax-wgl-batch"]
    assert hb
    assert {"explored", "depth", "frontier",
            "keys_running"} <= set(hb[-1]["args"])
    explored = [h["args"]["explored"] for h in hb]
    assert explored == sorted(explored), \
        "batch explored must stay monotone across compactions"
    snap = reg.snapshot()["counters"]
    real = sum(v for k, v in snap.items()
               if k.startswith("wgl.cells_real{")
               and "jax-wgl-batch" in k)
    total_rows = sum(len(e) for e, _ in pairs)
    assert real == total_rows
    padded = sum(v for k, v in snap.items()
                 if k.startswith("wgl.cells_padded{")
                 and "jax-wgl-batch" in k)
    assert padded > 0, "a 3-key batch pads to a power-of-two lane " \
                       "count and a common n bucket"


def test_progress_interval_throttles_trace_not_accounting():
    """progress-interval-s thins the trace emissions but the registry
    accounting (chunks, busy wall) stays exact per dispatch."""
    tr, reg = obs.Tracer(), obs.Registry()
    so = obs_search.SearchObs(tr, reg, min_interval_s=3600.0)
    for i in range(5):
        so.heartbeat("jax-wgl", iteration=i, chunk_s=0.01, frontier=1,
                     explored=i, depth=i)
    hb = [ev for ev in tr.events()
          if ev.get("name") == "wgl.heartbeat.jax-wgl"]
    assert len(hb) == 1, "only the first emission within the interval"
    assert reg.counter_value("wgl.chunks", engine="jax-wgl") == 5
    assert reg.gauge_value("wgl.states_explored", engine="jax-wgl") \
        == 4


# ---------------------------------------------------------------------------
# the run-scoped sink fix (satellite: heartbeat namespacing)

def test_capture_prefers_run_scoped_sinks_over_globals():
    """Two concurrent campaign cells: cell B binds last (owns the
    process-global pair), but cell A's search — capturing inside A's
    sink scope — must land its heartbeats in A's registry, under A's
    {campaign, cell} default labels."""
    tr_a = obs.Tracer(context={"campaign": "c", "cell": "a"})
    reg_a = obs.Registry(default_labels={"campaign": "c", "cell": "a"})
    tr_b = obs.Tracer(context={"campaign": "c", "cell": "b"})
    reg_b = obs.Registry(default_labels={"campaign": "c", "cell": "b"})
    with obs.bind(tr_a, reg_a):
        with obs.bind(tr_b, reg_b):          # B binds last: owns globals
            assert obs.registry() is reg_b
            with obs.sink_scope(tr_a, reg_a):
                so = obs_search.capture()
            so.heartbeat("jax-wgl", iteration=1, chunk_s=0.1,
                         frontier=5, explored=10, depth=2)
    assert reg_a.counter_value("wgl.chunks", engine="jax-wgl") == 1
    assert reg_b.counter_value("wgl.chunks", engine="jax-wgl") == 0
    key = "wgl.chunks{campaign=c,cell=a,engine=jax-wgl}"
    assert reg_a.snapshot()["counters"][key] == 1


def test_run_scope_pins_sinks_for_competition_threads():
    """obs.run_scope sets the contextvar AND the globals; a
    copy_context thread fan-out (the checker competition's spawn
    idiom) resolves the run's own pair even after a sibling rebinds
    the globals."""
    import contextvars
    test = {"obs?": True}
    got = {}
    with obs.run_scope(test):
        reg_mine = test["obs"]["registry"]
        other = obs.Registry()

        def worker():
            got["sinks"] = obs.current_sinks()

        ctx = contextvars.copy_context()
        with obs.bind(None, other):      # a sibling steals the globals
            t = threading.Thread(target=ctx.run, args=(worker,))
            t.start()
            t.join()
    assert got["sinks"][1] is reg_mine


def test_live_registries_exposes_every_open_bind():
    r1, r2 = obs.Registry(), obs.Registry()
    with obs.bind(None, r1):
        with obs.bind(None, r2):
            live = obs.live_registries()
            assert r1 in live and r2 in live
    assert obs.live_registries() == []


# ---------------------------------------------------------------------------
# live scrape: monotone explored/frontier on /api/metrics mid-search

@pytest.fixture
def token_server():
    server = web.serve({"ip": "127.0.0.1", "port": 0,
                        "token": "sekrit"})
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()


def _get(base, path, token=None):
    req = urllib.request.Request(base + path)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read().decode(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


def _prom_value(body, prefix):
    out = []
    for line in body.splitlines():
        if line.startswith(prefix) and not line.startswith("# "):
            out.append(float(line.rsplit(" ", 1)[1]))
    return out


@pytest.mark.slow
def test_live_search_exposes_monotone_progress_on_api_metrics(
        token_server):
    """THE acceptance criterion: while a device search runs, GET
    /api/metrics serves its explored-configs and frontier-occupancy
    series, and explored increases monotonically across scrapes. The
    401 gate is unchanged."""
    status, _, _ = _get(token_server, "/api/metrics")
    assert status == 401
    e, st = cas_register_spec.encode(_hist(1200, 16, seed=11))
    tr, reg = obs.Tracer(), obs.Registry()
    done = threading.Event()
    box = {}

    def search():
        with obs.bind(tr, reg):
            try:
                # 1-iteration dispatches: many heartbeats, so scrapes
                # land between them
                box["r"] = jax_wgl.check_encoded(
                    cas_register_spec, e, st, chunk_iters=1)
            finally:
                done.set()

    t = threading.Thread(target=search)
    t.start()
    explored_seen = []
    families_seen = set()
    try:
        while not done.is_set():
            _, body, _ = _get(token_server, "/api/metrics",
                              token="sekrit")
            explored_seen += _prom_value(
                body, "jepsen_wgl_states_explored{")
            for fam in ("jepsen_wgl_frontier_depth",
                        "jepsen_wgl_cells_real",
                        "jepsen_wgl_cells_padded",
                        "jepsen_wgl_device_busy_s"):
                if fam in body:
                    families_seen.add(fam)
            time.sleep(0.02)
    finally:
        t.join()
    assert box["r"]["valid"] in (True, False)
    assert explored_seen, "no mid-search scrape saw the explored gauge"
    assert explored_seen == sorted(explored_seen), \
        "explored-configs must increase monotonically"
    # the frontier + padding-accounting families were served mid-run
    assert len(families_seen) == 4, families_seen
    # the frontier gauge family was served too (final state persists)
    _, body, _ = _get(token_server, "/api/metrics", token="sekrit")
    # search finished: bind closed, so live_registries is empty again;
    # the SLO families from our own scrapes remain
    assert "jepsen_service_requests" in body
    assert "jepsen_service_request_s_bucket" in body


# ---------------------------------------------------------------------------
# service SLOs

def test_check_history_records_slo_histograms():
    hist = [{"type": "invoke", "process": 0, "f": "write", "value": 1},
            {"type": "ok", "process": 0, "f": "write", "value": 1},
            {"type": "invoke", "process": 0, "f": "read", "value": None},
            {"type": "ok", "process": 0, "f": "read", "value": 1}]
    out = service.check_history({"history": hist, "engine": "linear"})
    assert out["valid"] is True
    reg = service.slo_registry()
    h = reg.histogram("service.verdict_latency_s", endpoint="check",
                      valid="True")
    assert h is not None and h.count == 1
    qw = reg.histogram("service.queue_wait_s", endpoint="check")
    assert qw is not None and qw.count == 1
    body = service.metrics_text()
    assert "jepsen_service_verdict_latency_s_bucket" in body
    assert "jepsen_service_queue_wait_s_count" in body
    # deterministic render (same inputs -> same body)
    assert body == service.metrics_text()


def test_note_request_counts_errors_too():
    service.note_request("check", 400, 0.01)
    service.note_request("check", 200, 0.02)
    reg = service.slo_registry()
    assert reg.counter_value("service.requests", endpoint="check",
                             status="400") == 1
    assert reg.counter_value("service.requests", endpoint="check",
                             status="200") == 1
    assert reg.histogram("service.request_s",
                         endpoint="check").count == 2


def test_api_request_accounting_over_a_socket(token_server):
    # note_request runs in the handler's finally AFTER the response is
    # flushed, so the client can observe its body before the server
    # thread has counted it -- poll briefly instead of asserting the
    # instantaneous value
    def counted(status, want):
        reg = service.slo_registry()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            got = reg.counter_value("service.requests",
                                    endpoint="metrics", status=status)
            if got >= want:
                return got
            time.sleep(0.01)
        return reg.counter_value("service.requests",
                                 endpoint="metrics", status=status)

    _get(token_server, "/api/metrics", token="sekrit")
    _get(token_server, "/api/metrics", token="sekrit")
    assert counted("200", 2) >= 2
    # a 401 is accounted too
    _get(token_server, "/api/metrics")
    assert counted("401", 1) >= 1


# ---------------------------------------------------------------------------
# --profile capture

def test_profile_scope_unavailable_is_contained(tmp_path, monkeypatch):
    """The CI containment contract: JEPSEN_NO_PROFILER forces the
    profiler unavailable, the body still runs, and the marker records
    why."""
    monkeypatch.setenv("JEPSEN_NO_PROFILER", "1")
    assert not obs_profile.available()
    pdir = str(tmp_path / "prof" / "profile")
    test = {"profile?": True, "profile-dir": pdir}
    ran = []
    with obs_profile.scope(test) as captured:
        ran.append(captured)
    assert ran == [None]
    marker = json.loads(
        (tmp_path / "prof" / "profile.json").read_text())
    assert marker["status"] == "unavailable"


def test_profile_scope_captures_when_available(tmp_path):
    if not obs_profile.available():
        pytest.skip("jax.profiler unavailable in this environment")
    pdir = str(tmp_path / "prof" / "profile")
    test = {"profile?": True, "profile-dir": pdir,
            "profile-max-s": 30}
    with obs_profile.scope(test) as captured:
        assert captured == pdir
        # some device work to profile
        e, st = cas_register_spec.encode(_hist(100, 4))
        jax_wgl.check_encoded(cas_register_spec, e, st)
    marker = json.loads(
        (tmp_path / "prof" / "profile.json").read_text())
    assert marker["status"] == "done", marker
    assert os.path.isdir(pdir)


def test_profile_scope_never_raises_on_bad_dir(tmp_path):
    test = {"profile?": True,
            "profile-dir": "/proc/definitely/not/writable/x"}
    with obs_profile.scope(test):
        pass  # must not raise whatever the profiler did


def test_web_links_profile_marker(tmp_path, monkeypatch):
    """The home table links profile.json like the other obs
    artifacts."""
    fake = {"name": "t-prof", "start-time": "20260101T000000"}
    d = store.path(fake)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "results.json"), "w") as f:
        json.dump({"valid": True}, f)
    with open(os.path.join(d, "profile.json"), "w") as f:
        json.dump({"status": "done"}, f)
    page = web._home_page()
    assert "profile.json" in page


# ---------------------------------------------------------------------------
# campaign metrics fold

def _write_run_metrics(d, counters):
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "metrics.json"), "w") as f:
        json.dump({"counters": counters, "gauges": {},
                   "histograms": {}}, f)


def test_fold_campaign_metrics_sums_and_summarizes(tmp_path):
    cid = "fold-test"
    os.makedirs(store.campaign_path(cid), exist_ok=True)
    with open(store.campaign_path(cid, "campaign.json"), "w") as f:
        json.dump({"meta": {"id": cid, "cells": ["a", "b"]}}, f)
    # the coordinator's own snapshot carries the dispatcher's LIVE
    # cell-labelled re-folds of the same run metrics (plus its own
    # fleet counters): the fold must skip the re-folds — summing both
    # would double every wgl counter — while keeping the fleet series
    _write_run_metrics(store.campaign_path(cid), {
        "fleet.cells{outcome=True}": 2,
        "wgl.cells_real{bucket=64,cell=a,engine=jax-wgl}": 40,
        "wgl.cells_real{bucket=64,cell=b,engine=jax-wgl}": 40,
        "wgl.device_busy_s{cell=a,engine=jax-wgl}": 1.5,
        "wgl.device_busy_s{cell=b,engine=jax-wgl}": 1.5})
    from jepsen_tpu.campaign.journal import CampaignJournal
    jr = CampaignJournal(cid)
    runs = []
    for i, cell in enumerate(("a", "b")):
        d = os.path.join(store.base_dir, f"run-{cell}",
                         "20260101T00000" + str(i))
        _write_run_metrics(d, {
            "wgl.cells_real{bucket=64,cell=%s,engine=jax-wgl}"
            % cell: 40,
            "wgl.cells_padded{bucket=64,cell=%s,engine=jax-wgl}"
            % cell: 24,
            "wgl.device_busy_s{cell=%s,engine=jax-wgl}" % cell: 1.5})
        jr.append_cell({"cell": cell, "outcome": True, "path": d})
        runs.append(d)
    fold = obs_merge.fold_campaign_metrics(cid)
    assert fold["runs_folded"] == 3     # coordinator + 2 cell runs
    assert os.path.exists(store.campaign_path(cid,
                                              "metrics_fold.json"))
    # the coordinator's non-cell series folded; its cell-labelled
    # re-folds did NOT (the waste table below would otherwise double)
    assert fold["counters"]["fleet.cells{outcome=True}"] == 2
    summary = obs_merge.introspection_summary(fold, makespan_s=10.0)
    assert summary["padding"]["64"]["real"] == 80
    assert summary["padding"]["64"]["padded"] == 48
    assert summary["padding"]["64"]["waste_frac"] == \
        pytest.approx(48 / 128, abs=1e-4)
    assert summary["device_busy_total_s"] == pytest.approx(3.0)
    assert summary["duty_cycle"] == pytest.approx(0.3)
    # deterministic persist
    with open(store.campaign_path(cid, "metrics_fold.json"),
              "rb") as f:
        body = f.read()
    obs_merge.fold_campaign_metrics(cid)
    with open(store.campaign_path(cid, "metrics_fold.json"),
              "rb") as f:
        assert f.read() == body


def test_trace_summary_prints_waste_table(tmp_path):
    """The run summary renders the padding-waste table + duty cycle
    from a run's metrics.json."""
    import subprocess
    import sys
    d = str(tmp_path / "run")
    _write_run_metrics(d, {
        "wgl.cells_real{bucket=128,engine=jax-wgl}": 100,
        "wgl.cells_padded{bucket=128,engine=jax-wgl}": 28,
        "wgl.device_busy_s{engine=jax-wgl}": 0.5})
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "trace_summary.py"), d],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "padding waste" in out.stdout
    assert "128" in out.stdout
    assert "device duty cycle" in out.stdout


# ---------------------------------------------------------------------------
# planlint PL019

def _codes(diags, sev=None):
    return [d.code for d in diags
            if sev is None or d.severity == sev]


def test_pl019_rules(tmp_path):
    from jepsen_tpu.analysis.diagnostics import ERROR, WARNING

    # profile with telemetry disabled = error
    diags = planlint.lint_introspection({"profile?": True,
                                         "obs?": False,
                                         "name": "t"})
    assert "PL019" in _codes(diags, ERROR)
    # profile on an unnamed TEST MAP with no dir = error; a plain
    # options map (campaign/fleet lint) skips — cells are named at
    # build time
    diags = planlint.lint_introspection({"profile?": True,
                                         "checker": object()})
    assert "PL019" in _codes(diags, ERROR)
    assert planlint.lint_introspection({"profile?": True}) == []
    # unwritable profile-dir = error
    diags = planlint.lint_introspection(
        {"profile?": True,
         "profile-dir": "/proc/nope/never/profile"})
    assert "PL019" in _codes(diags, ERROR)
    # writable dir + named test = clean
    ok_dir = str(tmp_path / "prof")
    assert planlint.lint_introspection(
        {"profile?": True, "profile-dir": ok_dir}) == []
    assert planlint.lint_introspection(
        {"profile?": True, "name": "t"}) == []
    # cadence below the heartbeat interval = warning
    diags = planlint.lint_introspection(
        {"progress-interval-s": 0.1})
    assert "PL019" in _codes(diags, WARNING)
    # non-positive cadence = warning
    diags = planlint.lint_introspection(
        {"progress-interval-s": -1})
    assert "PL019" in _codes(diags, WARNING)
    # bad profile-max-s = warning
    diags = planlint.lint_introspection(
        {"profile?": True, "name": "t", "profile-max-s": 0})
    assert "PL019" in _codes(diags, WARNING)
    # sane knobs = clean
    assert planlint.lint_introspection(
        {"progress-interval-s": 5.0}) == []
    assert planlint.lint_introspection({}) == []


def test_pl019_rides_lint_plan():
    from jepsen_tpu import tests as tst
    from jepsen_tpu.analysis.diagnostics import WARNING
    t = tst.noop_test()
    t["ssh"] = {"dummy?": True}
    t["progress-interval-s"] = 0.01
    diags = [d for d in planlint.lint_plan(t) if d.code == "PL019"]
    assert diags and diags[0].severity == WARNING


# ---------------------------------------------------------------------------
# end to end: a profiled, introspected run

def test_run_with_profile_and_progress_interval(monkeypatch):
    """core.run with profile? on (profiler forced unavailable:
    containment) and a progress cadence still passes, persists the
    marker, and its metrics carry the padding accounting."""
    monkeypatch.setenv("JEPSEN_NO_PROFILER", "1")
    import random as _r
    from jepsen_tpu import core, generator as gen
    from jepsen_tpu import tests as tst
    from jepsen_tpu.checker import checkers as ck
    from jepsen_tpu.tests import Atom
    state = Atom(None)
    rng = _r.Random(3)
    t = tst.noop_test()
    t.update({
        "name": "introspect-e2e",
        "ssh": {"dummy?": True},
        "db": tst.atom_db(state),
        "client": tst.atom_client(state),
        "concurrency": 2,
        "profile?": True,
        "progress-interval-s": 30.0,
        "searchplan?": False,
        "generator": gen.clients(gen.limit(12, gen.mix([
            lambda: {"f": "read"},
            lambda: {"f": "write", "value": rng.randint(0, 3)},
        ]))),
        "checker": ck.linearizable({
            "model": "cas-register", "algorithm": "jax-wgl",
            "init-ops": [{"f": "write", "value": 0}]}),
    })
    test = core.run(t)
    assert test["results"]["valid"] is True, test["results"]
    marker = store.path(test, "profile.json")
    assert os.path.exists(marker)
    assert json.load(open(marker))["status"] == "unavailable"
    m = json.loads(
        open(store.path(test, "metrics.json")).read())
    assert any(k.startswith("wgl.cells_real{")
               for k in m["counters"])
    assert any(k.startswith("wgl.device_busy_s{")
               for k in m["counters"])
