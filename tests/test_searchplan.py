"""Search-plan analyzer (jepsen_tpu/analysis/searchplan.py): sealed
quiescent-cut segmentation, partition predicates, search-dead elision,
THE verdict-equivalence property (plan-on == plan-off, valid and
invalid, single- and multi-key, with and without crashes, and across
monitor chunk sizes 1/8/64), the quiescent-cut carry, planlint PL015,
jaxlint JX007, the per-value set reduction, the fleet-service planner,
and the History pairs-walk memoization."""

import pytest

from jepsen_tpu import analysis
from jepsen_tpu import history as h
from jepsen_tpu import independent, store
from jepsen_tpu import monitor as jmon
from jepsen_tpu.analysis import searchplan
from jepsen_tpu.checker import checkers as cks
from jepsen_tpu.checker import jax_wgl, wgl
from jepsen_tpu.checker.core import check_safe
from jepsen_tpu.models import base as mbase
from jepsen_tpu.robust import ChainedLatch

SPEC = mbase.model_spec("cas-register")


@pytest.fixture(autouse=True)
def store_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))


# ---------------------------------------------------------------------------
# history builders


class _Ev:
    """Tiny indexed event-list builder."""

    def __init__(self):
        self.events = []

    def __call__(self, t, p, f, v):
        self.events.append({"type": t, "process": p, "f": f, "value": v,
                            "index": len(self.events)})


def quiescent_hist(bursts=3, stale_read=False, crashed_read=False,
                   crashed_write=False):
    """Concurrent write||write bursts separated by sealing isolated
    writes; optional crashed ops and a trailing stale read (invalid
    only via the real search — value 0 was genuinely written)."""
    ev = _Ev()
    for j in range(bursts):
        x = j * 10
        ev("invoke", 0, "write", x)
        ev("invoke", 1, "write", x + 1)
        ev("ok", 0, "write", x)
        ev("ok", 1, "write", x + 1)
        if crashed_read:
            ev("invoke", 100 + j, "read", None)
            ev("info", 100 + j, "read", None)
        if crashed_write and j == 0:
            ev("invoke", 200, "write", 777)
            ev("info", 200, "write", 777)
        ev("invoke", 0, "write", x + 5)
        ev("ok", 0, "write", x + 5)
    ev("invoke", 2, "read", None)
    ev("ok", 2, "read", 0 if stale_read else (bursts - 1) * 10 + 5)
    return ev.events


def keyed_hist(nk=2, bad_key=None, crashed_read=False):
    """Independent [k v] register histories, quiescent per key."""
    ev = _Ev()
    t = independent.tuple_
    for k in range(nk):
        for j in range(3):
            x = j * 10
            ev("invoke", 2 * k, "write", t(k, x))
            ev("ok", 2 * k, "write", t(k, x))
            if crashed_read and j == 1:
                ev("invoke", 100 + k, "read", t(k, None))
                ev("info", 100 + k, "read", t(k, None))
            ev("invoke", 2 * k + 1, "read", t(k, None))
            ev("ok", 2 * k + 1, "read",
               t(k, 999 if (k == bad_key and j == 2) else x))
    return ev.events


# ---------------------------------------------------------------------------
# segmentation units


def test_sealed_cuts_found_and_seeded():
    segs, info = searchplan.segment_events(SPEC, quiescent_hist(3),
                                           min_segment=1)
    assert info["cuts"] >= 2
    assert len(segs) == info["cuts"] + 1
    # every later segment is seeded by the sealing write's pair
    for seg in segs[1:]:
        assert seg.seed is not None
        assert seg.seed["f"] == "write"
        # the seed's invoke AND ok events lead the segment
        assert seg.events[0]["index"] == seg.seed["index"]
    assert segs[0].seed is None


def test_min_segment_coalesces_cuts():
    hist = quiescent_hist(4)
    many, _ = searchplan.segment_events(SPEC, hist, min_segment=1)
    few, info = searchplan.segment_events(SPEC, hist, min_segment=6)
    assert len(few) < len(many)
    assert len(few) == info["cuts"] + 1


def test_crashed_write_blocks_all_later_cuts():
    """An unresolved :info write may linearize at ANY later point, so
    no instant after it is quiescent: segments are crash-isolated."""
    segs, info = searchplan.segment_events(
        SPEC, quiescent_hist(3, crashed_write=True), min_segment=1)
    # the crash lands in burst 0: at most the pre-crash cut(s) survive
    clean, _ = searchplan.segment_events(SPEC, quiescent_hist(3),
                                         min_segment=1)
    assert info["cuts"] < len(clean) - 1
    assert info["elided"] == 0


def test_crashed_reads_elide_and_cuts_survive():
    """A settled crashed read is unconstrained: elided, and the cuts
    it would straddle survive."""
    segs, info = searchplan.segment_events(
        SPEC, quiescent_hist(3, crashed_read=True), min_segment=1)
    assert info["elided"] == 3
    clean, cinfo = searchplan.segment_events(SPEC, quiescent_hist(3),
                                             min_segment=1)
    assert info["cuts"] == cinfo["cuts"]


def test_model_without_seal_fs_gets_no_cuts():
    mutex = mbase.model_spec("mutex")
    ev = _Ev()
    for j in range(4):
        ev("invoke", 0, "acquire", None)
        ev("ok", 0, "acquire", None)
        ev("invoke", 0, "release", None)
        ev("ok", 0, "release", None)
    segs, info = searchplan.segment_events(mutex, ev.events,
                                           min_segment=1)
    assert len(segs) == 1 and info["cuts"] == 0


def test_overlap_prevents_seal():
    """A write overlapped by another write cannot seal: the cut state
    would be ambiguous."""
    ev = _Ev()
    ev("invoke", 0, "write", 1)
    ev("invoke", 1, "write", 2)
    ev("ok", 0, "write", 1)
    ev("ok", 1, "write", 2)       # quiescent here, but NOT sealed
    ev("invoke", 0, "read", None)
    ev("ok", 0, "read", 2)
    segs, info = searchplan.segment_events(SPEC, ev.events,
                                           min_segment=1)
    assert info["cuts"] == 0


# ---------------------------------------------------------------------------
# THE equivalence property: plan-on == plan-off verdicts


def _lin():
    return cks.linearizable({"model": "cas-register",
                             "algorithm": "jax-wgl"})


HISTORIES = [
    ("valid-single", lambda: quiescent_hist(3), True),
    ("invalid-single-stale", lambda: quiescent_hist(3, stale_read=True),
     False),
    ("valid-single-crashes",
     lambda: quiescent_hist(3, crashed_read=True), True),
    ("invalid-single-crashes",
     lambda: quiescent_hist(3, stale_read=True, crashed_read=True,
                            crashed_write=True), False),
    ("valid-multikey", lambda: keyed_hist(2), True),
    ("invalid-multikey", lambda: keyed_hist(2, bad_key=1), False),
    ("valid-multikey-crashes",
     lambda: keyed_hist(2, crashed_read=True), True),
]


@pytest.mark.parametrize("name,build,expect",
                         HISTORIES, ids=[x[0] for x in HISTORIES])
def test_verdict_equivalence_plan_on_vs_off(name, build, expect):
    hist = build()
    keyed = any(independent.is_tuple(o.get("value")) for o in hist)
    checker = independent.checker(_lin()) if keyed else _lin()
    r_on = check_safe(checker, {"searchplan-min-segment": 1}, hist)
    r_off = check_safe(checker, {"searchplan?": False}, hist)
    assert r_on["valid"] is expect, (name, r_on)
    assert r_off["valid"] is expect, (name, r_off)


def test_planned_result_shape_and_witness():
    """A planned invalid verdict carries the failing segment's witness
    fields, the searchplan block, and summed diagnostics."""
    r = check_safe(_lin(), {"searchplan-min-segment": 1},
                   quiescent_hist(3, stale_read=True))
    assert r["valid"] is False
    sp = r.get("searchplan")
    assert sp and sp["segments"] >= 2
    assert "failed_segment" in sp
    assert "op" in r or "configs" in r   # witness survived the merge
    assert r["valid?"] is False


def test_plan_off_has_no_searchplan_block():
    r = check_safe(_lin(), {"searchplan?": False}, quiescent_hist(3))
    assert r["valid"] is True
    assert "searchplan" not in r


def test_partitions_without_crash_segments_skips_cut_execution():
    """searchplan-partitions=['per-key'] must stop the cut code on the
    EXECUTION paths too, not only in the analysis.json report."""
    # direct Linearizable path: no segmentation -> no searchplan block
    r = check_safe(_lin(), {"searchplan-min-segment": 1,
                            "searchplan-partitions": ["per-key"]},
                   quiescent_hist(3))
    assert r["valid"] is True
    assert "searchplan" not in r
    # independent batched path: per-key split still batches, but each
    # key rides as ONE unsegmented search
    chk = independent.checker(_lin())
    rk = check_safe(chk, {"searchplan-min-segment": 1,
                          "searchplan-partitions": ["per-key"]},
                    keyed_hist(2))
    assert rk["valid"] is True
    assert "searchplan" not in rk["results"][0]
    # the gate itself
    assert not searchplan.segments_enabled(
        {"searchplan-partitions": ["per-key"]})
    assert searchplan.segments_enabled({})
    assert not searchplan.segments_enabled({"searchplan?": False})


def test_confirm_opt_skips_planning():
    """Oracle confirmation changes the result contract; the planned
    path must step aside so the flat search honors it."""
    lin = cks.linearizable({"model": "cas-register",
                            "algorithm": "jax-wgl",
                            "engine_opts": {"confirm": True}})
    r = check_safe(lin, {"searchplan-min-segment": 1},
                   quiescent_hist(3, stale_read=True))
    assert r["valid"] is False
    assert "searchplan" not in r


def test_unsegmented_plan_counts_logical_ops():
    """per-key-only plans (no crash-segments) must report logical op
    counts, not raw invoke+completion event counts, or JX007 buckets
    on ~2x what spec.encode pads."""
    hist = keyed_hist(2)
    n_ops_per_key = {}
    for o in hist:
        v = o.get("value")
        if independent.is_tuple(v) and o["type"] == "invoke":
            n_ops_per_key[v.key] = n_ops_per_key.get(v.key, 0) + 1
    plan = searchplan.build_plan(
        {"checker": independent.checker(_lin()),
         "searchplan-partitions": ["per-key"]}, hist)
    assert len(plan.subsearches) == 2
    for s in plan.subsearches:
        assert s.n_ops <= max(n_ops_per_key.values()), s


def test_independent_batched_path_reports_segments():
    chk = independent.checker(_lin())
    r = check_safe(chk, {"searchplan-min-segment": 1}, keyed_hist(2))
    assert r["valid"] is True
    per = r["results"][0]
    assert per["searchplan"]["segments"] >= 2


# ---------------------------------------------------------------------------
# monitored path: equivalence across chunk sizes, with the carry on


def _feed(mon, ops):
    for i, op in enumerate(ops):
        mon.offer(dict(op, index=i))


@pytest.mark.parametrize("chunk", [1, 8, 64])
@pytest.mark.parametrize("stale", [False, True])
def test_monitor_equivalence_with_carry(chunk, stale):
    hist = quiescent_hist(3, stale_read=stale)
    e, st = SPEC.encode(hist)
    offline = wgl.check_encoded(SPEC, e, st)
    assert offline["valid"] is (not stale)
    latch = ChainedLatch()
    mon = jmon.Monitor(SPEC, latch, chunk=chunk, engine="wgl").start()
    _feed(mon, hist)
    mon.stop()
    s = mon.summary()
    assert s["verdict"] is offline["valid"], (chunk, stale, s)
    assert "quiescent_truncated_ops" in s


def test_monitor_carry_truncates_proven_prefix():
    """On a quiescent valid stream the encoder must shrink: chunk
    checks cover O(window), not O(prefix)."""
    hist = quiescent_hist(6)
    latch = ChainedLatch()
    mon = jmon.Monitor(SPEC, latch, chunk=4, engine="wgl").start()
    _feed(mon, hist)
    mon.stop()
    s = mon.summary()
    assert s["verdict"] is True
    assert s["quiescent_truncated_ops"] > 0
    # the surviving window is a fraction of the consumed stream
    enc = mon._encoders[None]
    assert len(enc) < s["ops_consumed"]


def test_monitor_carry_off_keeps_everything():
    hist = quiescent_hist(4)
    latch = ChainedLatch()
    mon = jmon.Monitor(SPEC, latch, chunk=4, engine="wgl",
                       quiescent_carry=False).start()
    _feed(mon, hist)
    mon.stop()
    s = mon.summary()
    assert s["verdict"] is True
    assert "quiescent_truncated_ops" not in s
    assert len(mon._encoders[None]) == s["ops_consumed"]


def test_stream_cut_blocked_by_open_read():
    """A still-open read may complete :ok later with a constraining
    value — it must block the carry (only SETTLED info reads are
    elidable)."""
    ev = _Ev()
    ev("invoke", 0, "write", 1)
    ev("ok", 0, "write", 1)
    ev("invoke", 1, "read", None)     # stays open
    ev("invoke", 0, "write", 2)
    ev("ok", 0, "write", 2)
    from jepsen_tpu.monitor.stream import StreamEncoder
    enc = StreamEncoder(SPEC)
    for i, op in enumerate(ev.events):
        enc.offer(op, i)
    e, _ = enc.materialize()
    cut = searchplan.stream_cut(SPEC, e)
    # the only legal cut is before the open read's invoke
    assert cut is None or cut[0] <= 2

    # settle the read as :info -> it elides, the later cut appears
    enc.offer({"type": "info", "process": 1, "f": "read",
               "value": None, "index": 5}, 5)
    e2, _ = enc.materialize()
    cut2 = searchplan.stream_cut(SPEC, e2)
    assert cut2 is not None and cut2[0] > 2


# ---------------------------------------------------------------------------
# per-value partitioning (set/add-read reduction)


def _set_hist(lost=False):
    ev = _Ev()
    for v in (1, 2, 3):
        ev("invoke", v, "add", v)
        ev("ok", v, "add", v)
    ev("invoke", 0, "read", None)
    ev("ok", 0, "read", [1, 3] if lost else [1, 2, 3])
    return ev.events


def test_per_value_parts_build_register_histories():
    parts = searchplan.per_value_parts(_set_hist())
    assert sorted(parts) == [1, 2, 3]
    reg = mbase.model_spec("register")
    for el, evs in parts.items():
        e, st = reg.encode(evs)
        assert wgl.check_encoded(reg, e, st)["valid"] is True


def test_per_value_read_before_add_stays_valid():
    # a read completing before add(e) sees e absent (0); the parts
    # must seed the register's "absent" state with an initial write 0
    # or every such VALID history checks false-invalid
    ev = _Ev()
    ev("invoke", 0, "read", None)
    ev("ok", 0, "read", [])
    ev("invoke", 0, "add", 1)
    ev("ok", 0, "add", 1)
    ev("invoke", 0, "read", None)
    ev("ok", 0, "read", [1])
    ev("invoke", 0, "add", 2)
    ev("ok", 0, "add", 2)
    ev("invoke", 0, "read", None)
    ev("ok", 0, "read", [1, 2])
    parts = searchplan.per_value_parts(ev.events)
    reg = mbase.model_spec("register")
    for el, evs in parts.items():
        e, st = reg.encode(evs)
        assert wgl.check_encoded(reg, e, st)["valid"] is True, el


def test_per_value_detects_lost_add():
    parts = searchplan.per_value_parts(_set_hist(lost=True))
    reg = mbase.model_spec("register")
    verdicts = {}
    for el, evs in parts.items():
        e, st = reg.encode(evs)
        verdicts[el] = wgl.check_encoded(reg, e, st)["valid"]
    assert verdicts == {1: True, 2: False, 3: True}


def test_per_value_not_applicable_to_registers():
    assert searchplan.per_value_parts(quiescent_hist(2)) is None


# ---------------------------------------------------------------------------
# the plan report (checker.core.plan_history)


def test_plan_report_persists_in_analysis():
    chk = independent.checker(_lin())
    test = {"checker": chk, "searchplan-min-segment": 1}
    check_safe(chk, test, keyed_hist(2))
    report = test["analysis"]["searchplan"]
    assert report["summary"]["subsearches"] >= 2
    codes = [d["code"] for d in report["diagnostics"]]
    assert "SP001" in codes and "SP004" in codes


def test_plan_report_runs_once_per_test():
    chk = independent.checker(_lin())
    test = {"checker": chk, "searchplan-min-segment": 1}
    hist = h.ensure_indexed(keyed_hist(2))
    from jepsen_tpu.checker.core import plan_history
    plan_history(test, hist)
    marker = test["analysis"]["searchplan"]
    plan_history(test, hist)
    assert test["analysis"]["searchplan"] is marker


def test_plan_opt_out():
    chk = independent.checker(_lin())
    test = {"checker": chk, "searchplan?": False}
    check_safe(chk, test, keyed_hist(2))
    assert "searchplan" not in test.get("analysis", {})


def test_sp005_single_search_warns():
    plan = searchplan.build_plan({"searchplan-min-segment": 1},
                                 quiescent_hist(1)[:4], lin=_lin(),
                                 keyed=False)
    # 2-op history: nothing to cut -> single sub-search + SP005
    assert len(plan.subsearches) == 1
    assert "SP005" in [d.code for d in plan.diagnostics]


def test_sp007_unknown_predicate():
    plan = searchplan.build_plan(
        {"searchplan-partitions": ["per-key", "bogus"],
         "searchplan-min-segment": 1},
        keyed_hist(2), lin=_lin(), keyed=True)
    assert "SP007" in [d.code for d in plan.diagnostics]
    assert len(plan.subsearches) >= 2    # per-key still applied


# ---------------------------------------------------------------------------
# planlint PL015


def _plan_map(**kw):
    from jepsen_tpu import client as jc, generator as gen
    base = {"client": jc.noop, "generator": gen.limit(
        1, gen.repeat({"f": "read"})), "concurrency": 1}
    base.update(kw)
    return base


def test_pl015_unknown_predicate_is_error():
    diags = analysis.planlint.searchplan_diags(
        {"searchplan-partitions": ["per-key", "nope"]})
    errs = [d for d in diags if d.code == "PL015"
            and d.severity == "error"]
    assert errs and "nope" in errs[0].message


def test_pl015_known_predicates_clean():
    assert not analysis.planlint.searchplan_diags(
        {"searchplan-partitions": ["per-key", "per-value",
                                   "crash-segments"]})


def test_pl015_bad_min_segment_warns():
    diags = analysis.planlint.searchplan_diags(
        {"searchplan-min-segment": 0})
    assert [d for d in diags if d.code == "PL015"
            and d.severity == "warning"]


def test_pl015_enabled_without_plannable_gate_warns():
    from jepsen_tpu import checker as cc
    diags = analysis.planlint.searchplan_diags(
        {"searchplan?": True, "checker": cc.noop()})
    assert [d for d in diags if d.code == "PL015"]
    # with a linearizable gate: clean
    assert not analysis.planlint.searchplan_diags(
        {"searchplan?": True, "checker": _lin()})


def test_pl015_monitor_without_carry_warns():
    diags = analysis.planlint.searchplan_diags(
        {"monitor": {"quiescent-carry?": False}, "checker": _lin()})
    assert [d for d in diags if d.code == "PL015"]
    diags2 = analysis.planlint.searchplan_diags(
        {"monitor": True, "searchplan?": False, "checker": _lin()})
    assert [d for d in diags2 if d.code == "PL015"]
    assert not analysis.planlint.searchplan_diags(
        {"monitor": True, "checker": _lin()})


def test_pl015_skip_offline_with_carry_warns():
    # skip-offline? makes the monitor verdict final, so the
    # quiescent-cut carry loses its offline backstop
    diags = analysis.planlint.searchplan_diags(
        {"monitor": {"skip-offline?": True}, "checker": _lin()})
    assert [d for d in diags if d.code == "PL015"
            and "skip-offline" in d.message]
    # carry off alongside it: the combination rule stays quiet (the
    # no-carry warning fires instead)
    diags2 = analysis.planlint.searchplan_diags(
        {"monitor": {"skip-offline?": True, "quiescent-carry?": False},
         "checker": _lin()})
    assert not [d for d in diags2 if "skip-offline" in d.message]


def test_pl015_flows_through_lint_plan():
    diags = analysis.lint_plan(_plan_map(
        **{"searchplan-partitions": ["bogus"]}))
    assert [d for d in diags if d.code == "PL015"]


# ---------------------------------------------------------------------------
# jaxlint JX007


def test_jx007_shape_proliferation():
    from jepsen_tpu.analysis import jaxlint
    # 6 distinct pow-2 buckets > MAX_PLAN_SHAPES
    diags = jaxlint.lint_searchplan_shapes([8, 20, 40, 80, 300, 900,
                                            2000])
    assert [d for d in diags if d.code == "JX007"]
    assert "set_n_floor" in diags[0].fix_hint
    # same sizes, generous floor -> one bucket, clean
    from jepsen_tpu.campaign import compile_cache
    prior = compile_cache.n_floor()
    compile_cache.set_n_floor(4096)
    try:
        assert not jaxlint.lint_searchplan_shapes(
            [8, 20, 40, 80, 300, 900, 2000])
    finally:
        compile_cache.set_n_floor(prior)


def test_jx007_few_shapes_clean():
    from jepsen_tpu.analysis import jaxlint
    assert not jaxlint.lint_searchplan_shapes([8, 8, 9, 15, 16, 16])


# ---------------------------------------------------------------------------
# fleet service planning


def test_service_check_plans_and_matches():
    from jepsen_tpu.fleet import service
    hist = quiescent_hist(3)
    on = service.check_history({"history": hist, "model":
                                "cas-register"})
    off = service.check_history({"history": hist, "model":
                                 "cas-register", "searchplan": False})
    assert on["valid"] is True and off["valid"] is True
    assert on.get("searchplan", {}).get("segments", 0) >= 2 \
        or "searchplan" not in on   # min-segment may coalesce
    bad_on = service.check_history(
        {"history": quiescent_hist(3, stale_read=True),
         "model": "cas-register"})
    bad_off = service.check_history(
        {"history": quiescent_hist(3, stale_read=True),
         "model": "cas-register", "searchplan": False})
    assert bad_on["valid"] is False and bad_off["valid"] is False


# ---------------------------------------------------------------------------
# History memoization (the shared index/pairs walk)


def test_ensure_indexed_idempotent():
    hist = h.ensure_indexed(quiescent_hist(2))
    assert isinstance(hist, h.History)
    assert h.ensure_indexed(hist) is hist


def test_pairs_memoized_on_history():
    hist = h.ensure_indexed(quiescent_hist(2))
    assert h.pairs(hist) is h.pairs(hist)
    # plain lists keep the old no-cache behavior
    plain = quiescent_hist(2)
    assert h.pairs(plain) is not h.pairs(plain)


def test_pairs_cache_not_shared_across_objects():
    a = h.ensure_indexed(quiescent_hist(2))
    b = h.ensure_indexed(quiescent_hist(2))
    assert h.pairs(a) is not h.pairs(b)


# ---------------------------------------------------------------------------
# merge helper


def test_merge_segment_results_shapes():
    merged = searchplan.merge_segment_results(
        [{"valid": True, "configs_explored": 3, "iterations": 2},
         {"valid": False, "configs_explored": 5, "iterations": 7,
          "op": {"f": "read"}},
         {"valid": True, "configs_explored": 1, "iterations": 1}])
    assert merged["valid"] is False
    assert merged["configs_explored"] == 9
    assert merged["iterations"] == 7
    assert merged["op"] == {"f": "read"}
    assert merged["searchplan"]["failed_segment"] == 1

    unk = searchplan.merge_segment_results(
        [{"valid": True}, {"valid": "unknown", "error": "timeout"}])
    assert unk["valid"] == "unknown"
    assert unk["error"] == "timeout"
