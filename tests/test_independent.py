"""Tests for jepsen_tpu.independent: tuples, sequential/concurrent
generators, and the per-key checker with its batched device fast path
(reference independent.clj + independent_test.clj semantics)."""

import pytest

from jepsen_tpu import checker as cc
from jepsen_tpu import generator as gen
from jepsen_tpu import history as h
from jepsen_tpu import independent
from jepsen_tpu.checker import checkers as ck
from jepsen_tpu.generator import testing as gt

inv = h.invoke_op
ok = h.ok_op
T = independent.tuple_


def test_tuple():
    t = T("k", 5)
    assert independent.is_tuple(t)
    assert t.key == "k" and t.value == 5
    assert not independent.is_tuple(("k", 5))
    assert not independent.is_tuple([1, 2])
    assert list(t) == ["k", 5]   # serializes like a 2-list


def test_sequential_generator():
    g = independent.sequential_generator(
        [0, 1], lambda k: gen.limit(2, gen.repeat({"f": "w", "value": "x"})))
    hist = [o for o in gt.quick(gen.clients(g)) if h.invoke(o)]
    vals = [o["value"] for o in hist]
    assert vals == [T(0, "x"), T(0, "x"), T(1, "x"), T(1, "x")]


def test_history_keys_and_subhistory():
    hist = [
        inv(0, "w", T("a", 1)),
        h.op("info", "nemesis", "start", "whoops"),
        ok(0, "w", T("a", 1)),
        inv(1, "w", T("b", 2)),
        ok(1, "w", T("b", 2)),
    ]
    assert independent.history_keys(hist) == {"a", "b"}
    sub = independent.subhistory("a", hist)
    # unkeyed nemesis op appears; key b's ops don't; values unwrapped
    assert [o.get("value") for o in sub] == [1, "whoops", 1]


def test_concurrent_generator_groups_and_rotation():
    """2 threads per key over 4 worker threads: two keys in flight;
    exhausted groups rotate to fresh keys (independent.clj:103-236)."""
    n_per_key = 2
    g = independent.concurrent_generator(
        n_per_key, range(10),
        lambda k: gen.limit(3, gen.repeat({"f": "w", "value": k})))
    test = {"concurrency": 4, "nodes": ["n1", "n2"]}
    hist = gt.simulate(test, g, gt.perfect)
    invs = [o for o in hist if h.invoke(o)]
    # every op carries a tuple value wrapping its key
    assert all(independent.is_tuple(o["value"]) for o in invs)
    by_key = {}
    for o in invs:
        by_key.setdefault(o["value"].key, []).append(o)
    # each key gets exactly its 3 ops, all 10 keys eventually run
    assert set(by_key) == set(range(10))
    assert all(len(ops) == 3 for ops in by_key.values())
    # each key is executed by exactly one group of n threads
    for k, ops in by_key.items():
        assert len({o["process"] % 4 for o in ops}) <= n_per_key
    # two keys genuinely interleave at the start (two groups in parallel)
    first8 = [o["value"].key for o in invs[:8]]
    assert len(set(first8)) >= 2


def test_concurrent_generator_concurrency_assertion():
    g = independent.concurrent_generator(
        8, [0], lambda k: gen.once({"f": "w"}))
    test = {"concurrency": 4, "nodes": ["n1"]}
    with pytest.raises(Exception, match="concurrency"):
        gt.simulate(test, g, gt.perfect)


def _keyed_history(keys, bad_keys=()):
    """Valid (or corrupted) per-key cas-register histories interleaved."""
    hist = []
    for i, k in enumerate(keys):
        p = i % 3
        hist += [
            inv(p, "write", T(k, 1)),
            ok(p, "write", T(k, 1)),
            inv(p, "read", T(k, None)),
            ok(p, "read", T(k, 99 if k in bad_keys else 1)),
        ]
    return hist


def test_independent_checker_splits_and_merges():
    c = independent.checker(ck.linearizable({"model": "cas-register",
                                             "algorithm": "wgl"}))
    r = cc.check(c, {}, _keyed_history(["a", "b", "c"], bad_keys={"b"}))
    assert r["valid"] is False
    assert r["failures"] == ["b"]
    assert r["results"]["a"]["valid"] is True
    assert r["results"]["b"]["valid"] is False
    assert r["results"]["c"]["valid"] is True


def test_independent_checker_all_valid():
    c = independent.checker(ck.linearizable({"model": "cas-register",
                                             "algorithm": "wgl"}))
    r = cc.check(c, {}, _keyed_history(list(range(4))))
    assert r["valid"] is True
    assert r["failures"] == []


def test_independent_batched_single_device_call(monkeypatch):
    """With a device-engine Linearizable inner checker, ALL keys go to
    parallel.check_batch_encoded in ONE call (the TPU fast path)."""
    from jepsen_tpu import parallel

    calls = []
    real = parallel.check_batch_encoded

    def counting(spec, pairs, **kw):
        calls.append(len(pairs))
        return real(spec, pairs, **kw)

    monkeypatch.setattr(parallel, "check_batch_encoded", counting)
    c = independent.checker(ck.linearizable({"model": "cas-register",
                                             "algorithm": "jax-wgl"}))
    keys = list(range(6))
    r = cc.check(c, {}, _keyed_history(keys, bad_keys={2, 4}))
    assert calls == [6]        # one batched call for all six keys
    assert r["valid"] is False
    assert sorted(r["failures"]) == [2, 4]
    for k in keys:
        assert r["results"][k]["valid"] is (k not in (2, 4))


def test_independent_batched_through_compose(monkeypatch):
    """The register workload wraps Linearizable in a compose with
    timeline; the fast path must still batch the linearizable member and
    run the other members per key."""
    from jepsen_tpu import parallel
    from jepsen_tpu.checker import timeline

    calls = []
    real = parallel.check_batch_encoded

    def counting(spec, pairs, **kw):
        calls.append(len(pairs))
        return real(spec, pairs, **kw)

    monkeypatch.setattr(parallel, "check_batch_encoded", counting)
    c = independent.checker(cc.compose({
        "linearizable": ck.linearizable({"model": "cas-register",
                                         "algorithm": "jax-wgl"}),
        "timeline": timeline.html(),
    }))
    keys = ["a", "b", "c"]
    r = cc.check(c, {}, _keyed_history(keys, bad_keys={"b"}))
    assert calls == [3]
    assert r["valid"] is False
    assert r["failures"] == ["b"]
    for k in keys:
        kr = r["results"][k]
        assert kr["linearizable"]["valid"] is (k != "b")
        assert kr["timeline"]["valid"] is True
        assert kr["valid"] is (k != "b")


def test_independent_nonlinearizable_inner_uses_pmap():
    """A non-Linearizable inner checker goes through the per-key path."""
    seen = []

    class Probe(cc.Checker):
        def check(self, test, hist, opts=None):
            seen.append(opts.get("history-key"))
            return {"valid": True}

    c = independent.checker(Probe())
    r = cc.check(c, {}, _keyed_history(["x", "y"]))
    assert r["valid"] is True
    assert sorted(seen) == ["x", "y"]


def test_independent_per_key_store_files(tmp_path, monkeypatch):
    from jepsen_tpu import store
    monkeypatch.setattr(store, "base_dir", str(tmp_path))
    test = {"name": "indy", "start-time": "20260729T000000.000000+0000",
            "nodes": []}
    c = independent.checker(ck.linearizable({"model": "cas-register",
                                             "algorithm": "wgl"}))
    cc.check(c, test, _keyed_history(["a"]))
    import os
    d = store.path(test, independent.DIR, "a")
    assert sorted(os.listdir(d)) == ["history.txt", "results.json"]


def _hard_keyed_history(keys):
    """Per-key ~150-op corrupt-but-in-range cas histories (the search,
    not the state abstraction, must decide them), values wrapped in
    independent tuples with disjoint per-key processes."""
    import random as _r

    from jepsen_tpu.simulate import corrupt, random_history
    hist = []
    idx = 0
    for k in keys:
        h = corrupt(_r.Random(100 + k),
                    random_history(_r.Random(k), "cas-register", 6, 150,
                                   0.05))
        for o in h:
            if o["type"] == "ok" and o["f"] == "read" \
                    and o.get("value") is not None:
                o["value"] = o["value"] % 4
        for o in h:
            o = dict(o)
            o["process"] = o["process"] + 10 * k
            o["value"] = T(k, o.get("value"))
            o["index"] = idx
            idx += 1
            hist.append(o)
    return hist


def test_direct_and_batched_paths_filter_identically():
    """VERDICT r4 weak #7: the direct Linearizable.check and the batched
    independent path must select the same client ops (one shared
    history.client_ops), so a nemesis-laced history with init ops gets
    identical verdicts on both paths."""
    keys = ["a", "b", "c"]
    hist = _keyed_history(keys, bad_keys={"b"})
    # lace with nemesis ops and a non-client log-ish op (string process)
    laced = [h.op("info", "nemesis", "start-partition", "part")]
    for i, o in enumerate(hist):
        laced.append(o)
        if i % 3 == 0:
            laced.append(h.op("info", "nemesis", "kill", None))
    laced.append(h.op("info", "logger", "snarf", "n1.log"))
    opts = {"model": "cas-register", "init-ops": [{"f": "write",
                                                   "value": 1}]}
    batched = cc.check(
        independent.checker(ck.linearizable({**opts,
                                             "algorithm": "jax-wgl"})),
        {}, laced)
    for k in keys:
        direct = cc.check(ck.linearizable({**opts, "algorithm": "wgl"}),
                          {}, independent.subhistory(k, laced))
        assert batched["results"][k]["valid"] == direct["valid"], k
    assert batched["failures"] == ["b"]


def test_independent_engine_opts_checkpoint_flows_through(tmp_path,
                                                          monkeypatch):
    """engine_opts reach the batched device call: a checkpoint path set
    on the inner linearizable checker produces a batch snapshot when the
    check is interrupted, and a rerun resumes it (the documented
    long-run resume surface)."""
    import os

    from jepsen_tpu import parallel

    # assert the BATCHED path actually ran: the silent per-key fallback
    # would also write checkpoints and mask a broken batched call
    calls = []
    real = parallel.check_batch_encoded

    def counting(spec, pairs, **kw):
        calls.append((len(pairs), kw.get("checkpoint")))
        return real(spec, pairs, **kw)

    monkeypatch.setattr(parallel, "check_batch_encoded", counting)

    ck_path = str(tmp_path / "indep.npz")
    keys = list(range(4))
    c = independent.checker(ck.linearizable(
        {"model": "cas-register", "algorithm": "jax-wgl",
         "engine_opts": {"checkpoint": ck_path, "timeout_s": 0,
                         "chunk_iters": 1, "checkpoint_every_s": 0}}))
    r = cc.check(c, {}, _hard_keyed_history(keys))
    # the search planner may slice keys into more than one segment per
    # key: at least one pair per key, and the checkpoint path must
    # reach the batch either way
    assert calls and calls[0][0] >= 4 and calls[0][1] == ck_path
    # interrupted: some keys unknown, snapshot on disk
    assert os.path.exists(ck_path)
    assert any(res["valid"] == "unknown"
               for res in r["results"].values())
    # rerun with full budget: resumes and decides everything
    c2 = independent.checker(ck.linearizable(
        {"model": "cas-register", "algorithm": "jax-wgl",
         "engine_opts": {"checkpoint": ck_path}}))
    r2 = cc.check(c2, {}, _hard_keyed_history(keys))
    assert all(res["valid"] in (True, False)
               for res in r2["results"].values())
    assert not os.path.exists(ck_path)
