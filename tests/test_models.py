"""Model tests: oracle semantics + numpy/jax step equivalence."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jepsen_tpu.history import NIL
from jepsen_tpu import models as m


# -- oracle semantics --------------------------------------------------------

def test_register_oracle():
    r = m.register()
    r = r.step({"f": "write", "value": 3})
    assert r == m.register(3)
    assert r.step({"f": "read", "value": 3}) == r
    assert r.step({"f": "read", "value": None}) == r
    assert m.is_inconsistent(r.step({"f": "read", "value": 4}))


def test_cas_register_oracle():
    r = m.cas_register(1)
    r2 = r.step({"f": "cas", "value": [1, 2]})
    assert r2 == m.cas_register(2)
    assert m.is_inconsistent(r.step({"f": "cas", "value": [3, 4]}))


def test_mutex_oracle():
    x = m.mutex()
    x2 = x.step({"f": "acquire"})
    assert x2.locked
    assert m.is_inconsistent(x2.step({"f": "acquire"}))
    assert not x2.step({"f": "release"}).locked
    assert m.is_inconsistent(x.step({"f": "release"}))


def test_fifo_queue_oracle():
    q = m.fifo_queue()
    q = q.step({"f": "enqueue", "value": 1}).step({"f": "enqueue", "value": 2})
    assert m.is_inconsistent(q.step({"f": "dequeue", "value": 2}))
    q2 = q.step({"f": "dequeue", "value": 1})
    assert q2 == m.fifo_queue(2)
    assert m.is_inconsistent(m.fifo_queue().step({"f": "dequeue", "value": 1}))


def test_unordered_queue_oracle():
    q = m.unordered_queue()
    q = q.step({"f": "enqueue", "value": 1}).step({"f": "enqueue", "value": 2})
    q2 = q.step({"f": "dequeue", "value": 2})
    assert q2 == m.unordered_queue(1)
    assert m.is_inconsistent(q.step({"f": "dequeue", "value": 9}))


def test_multi_register_oracle():
    r = m.multi_register({"x": 1, "y": 2})
    r2 = r.step({"f": "write", "value": {"x": 5}})
    assert r2.values == {"x": 5, "y": 2}
    assert r2.step({"f": "read", "value": {"x": 5, "y": 2}}) == r2
    assert m.is_inconsistent(r2.step({"f": "read", "value": {"x": 1}}))


# -- numpy/jax step equivalence ----------------------------------------------

def _random_args(rng, spec, s0):
    f = rng.integers(0, len(spec.f_codes))
    args = rng.integers(-2, 4, size=spec.arg_width).astype(np.int32)
    ret = rng.integers(-2, 4, size=spec.arg_width).astype(np.int32)
    # sprinkle NILs
    args[rng.random(spec.arg_width) < 0.3] = NIL
    ret[rng.random(spec.arg_width) < 0.3] = NIL
    return np.int32(f), args, ret


@pytest.mark.parametrize("spec_name", [
    "register", "cas-register", "mutex", "fifo-queue", "unordered-queue"])
def test_step_np_jax_equivalence(spec_name):
    spec = m.model_spec(spec_name)
    S = 6 if "queue" in spec_name else spec.state_size(None)

    class FakeEnc:
        f = np.array([0] * 5, np.int32)  # 5 enqueues worth of capacity

    if "queue" in spec_name:
        s0 = spec.init_state(FakeEnc(), S)
    else:
        s0 = spec.init_state(None, S)
    s0 = np.asarray(s0, np.int32)

    jstep = jax.jit(lambda s, f, a, r: spec.step(s, f, a, r, jnp))
    rng = np.random.default_rng(7)
    state = s0
    for _ in range(200):
        f, args, ret = _random_args(rng, spec, state)
        ns_np, ok_np = spec.step(state, f, args, ret, np)
        ns_j, ok_j = jstep(state, f, args, ret)
        assert bool(ok_np) == bool(ok_j), (spec_name, f, args, ret, state)
        np.testing.assert_array_equal(np.asarray(ns_np), np.asarray(ns_j))
        if bool(ok_np):
            state = np.asarray(ns_np, np.int32)


def test_register_tensor_matches_oracle():
    spec = m.register_spec
    s0 = np.full(1, NIL, np.int32)
    ns, ok = spec.step(s0, np.int32(1), np.array([7], np.int32),
                       np.array([NIL], np.int32), np)
    assert bool(ok) and ns[0] == 7
    # read of wrong value fails
    _, ok = spec.step(ns, np.int32(0), np.array([NIL], np.int32),
                      np.array([8], np.int32), np)
    assert not bool(ok)
    # read of NIL (unknown) is ok
    _, ok = spec.step(ns, np.int32(0), np.array([NIL], np.int32),
                      np.array([NIL], np.int32), np)
    assert bool(ok)
