"""checker.core edge cases: merge_valid /
valid_prio over None/"unknown"/mixed inputs, check_safe's exception
containment, and the once-per-test histlint hook's idempotence and
containment."""

import threading

import pytest

from jepsen_tpu import checker as jchecker
from jepsen_tpu import history as h
from jepsen_tpu.checker.core import (check_safe, merge_valid,
                                     valid_prio)


def hist():
    return h.parse_history_edn_like([
        ("invoke", 0, "read", None),
        ("ok", 0, "read", 1),
    ])


# ---------------------------------------------------------------------------
# validity lattice

def test_valid_prio_ordering():
    assert valid_prio(False) == 0
    assert valid_prio("unknown") == 1
    assert valid_prio(None) == 1
    assert valid_prio(True) == 2
    # any other truthy value ranks like True (checker.clj's :else)
    assert valid_prio("yep") == 2


def test_merge_valid_lattice():
    assert merge_valid([]) is True
    assert merge_valid([True, True]) is True
    assert merge_valid([True, "unknown"]) == "unknown"
    assert merge_valid([None, True]) is None          # None ~ unknown
    assert merge_valid(["unknown", False, True]) is False
    assert merge_valid([False]) is False
    # False dominates regardless of order
    assert merge_valid([True, "unknown", False, None]) is False


# ---------------------------------------------------------------------------
# check_safe containment

def test_check_safe_checker_raises_becomes_unknown():
    def boom(test, hist_, opts):
        raise RuntimeError("kaboom")

    res = check_safe(boom, {}, hist())
    assert res["valid"] == "unknown"
    assert "kaboom" in res["error"]
    assert "RuntimeError" in res["error"]


def test_check_safe_passes_through_unknown_and_false():
    assert check_safe(lambda t, hh, o: {"valid": "unknown"},
                      {}, hist())["valid"] == "unknown"
    assert check_safe(lambda t, hh, o: {"valid": False},
                      {}, hist())["valid"] is False


def test_check_safe_malformed_history_becomes_unknown():
    """ensure_indexed raises HistoryError on junk events; check_safe
    contains it."""
    res = check_safe(jchecker.noop(), {}, ["not-an-op"])
    assert res["valid"] == "unknown"
    assert "HistoryError" in res["error"]


def test_compose_merges_and_survives_a_raising_subchecker():
    def boom(test, hist_, opts):
        raise ValueError("sub-checker died")

    c = jchecker.compose({
        "good": jchecker.unbridled_optimism(),
        "bad": boom,
    })
    res = check_safe(c, {}, hist())
    assert res["valid"] == "unknown"
    assert res["good"]["valid"] is True
    assert res["bad"]["valid"] == "unknown"


def test_compose_false_dominates_unknown():
    c = jchecker.compose({
        "f": lambda t, hh, o: {"valid": False},
        "u": lambda t, hh, o: {"valid": "unknown"},
        "t": jchecker.noop(),
    })
    assert check_safe(c, {}, hist())["valid"] is False


# ---------------------------------------------------------------------------
# the histlint hook

def test_lint_runs_once_per_test_map():
    test = {}
    c = jchecker.compose({f"n{i}": jchecker.noop() for i in range(8)})
    check_safe(c, test, hist())
    # one report despite 8 subcheckers fanning through check()
    assert test["analysis-done?"] is True
    assert "history" in test["analysis"]
    before = test["analysis"]["history"]
    check_safe(c, test, hist())
    assert test["analysis"]["history"] is before


def test_lint_hook_is_thread_safe():
    test = {}
    barrier = threading.Barrier(8)
    done = []

    def worker():
        barrier.wait()
        check_safe(jchecker.noop(), test, hist())
        done.append(1)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(done) == 8
    assert "history" in test["analysis"]


def test_lint_crash_never_changes_verdict(monkeypatch):
    from jepsen_tpu import analysis

    def explode(*a, **kw):
        raise RuntimeError("lint bug")

    monkeypatch.setattr(analysis, "run_analyzer", explode)
    test = {}
    res = check_safe(jchecker.unbridled_optimism(), test, hist())
    assert res["valid"] is True


def test_non_dict_test_is_tolerated():
    res = check_safe(jchecker.unbridled_optimism(), None, hist())
    assert res["valid"] is True


@pytest.mark.parametrize("opt_out", [False, 0, None])
def test_analysis_opt_out_values(opt_out):
    test = {"analysis?": opt_out}
    check_safe(jchecker.noop(), test, hist())
    assert "analysis" not in test
