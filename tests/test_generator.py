"""Generator combinator tests via the simulated-time harness (mirrors
reference test/jepsen/generator_test.clj, 532 LoC, which asserts exact op
sequences under deterministic randomness)."""

import pytest

from jepsen_tpu import generator as gen
from jepsen_tpu.generator import testing as gt


def invocations(h):
    return [o for o in h if o["type"] == "invoke"]


def test_nil_is_exhausted():
    assert gt.quick(None) == []


def test_map_is_one_shot():
    h = gt.quick({"f": "write", "value": 2})
    assert len(h) == 2  # invoke + ok
    assert h[0]["f"] == "write" and h[0]["type"] == "invoke"
    assert h[0]["time"] == 0
    assert h[1]["type"] == "ok"
    assert h[1]["time"] == gt.PERFECT_LATENCY


def test_sequence_chains():
    h = invocations(gt.quick([{"f": "a"}, {"f": "b"}, {"f": "c"}]))
    assert [o["f"] for o in h] == ["a", "b", "c"]


def test_function_generator():
    count = {"n": 0}

    def f():
        count["n"] += 1
        if count["n"] > 3:
            return None
        return {"f": "w", "value": count["n"]}

    h = invocations(gt.quick(f))
    assert [o["value"] for o in h] == [1, 2, 3]


def test_limit():
    h = invocations(gt.quick(gen.limit(3, gen.repeat({"f": "r"}))))
    assert len(h) == 3


def test_once():
    h = invocations(gt.quick(gen.once(gen.repeat({"f": "r"}))))
    assert len(h) == 1


def test_repeat_bounded():
    h = invocations(gt.quick(gen.repeat(5, {"f": "r"})))
    assert len(h) == 5
    assert all(o["f"] == "r" for o in h)


def test_mix_uses_all():
    g = gen.mix([gen.repeat(4, {"f": "a"}), gen.repeat(4, {"f": "b"})])
    h = invocations(gt.quick(g))
    fs = {o["f"] for o in h}
    assert fs == {"a", "b"}
    assert len(h) == 8


def test_filter():
    xs = [{"f": "w", "value": i} for i in range(8)]
    g = gen.filter(lambda op: op["value"] % 2 == 0, xs)
    h = invocations(gt.quick(g))
    assert [o["value"] for o in h] == [0, 2, 4, 6]


def test_map_transform():
    g = gen.map(lambda op: {**op, "value": op["value"] * 10},
                [{"f": "w", "value": 1}, {"f": "w", "value": 2}])
    h = invocations(gt.quick(g))
    assert [o["value"] for o in h] == [10, 20]


def test_f_map():
    g = gen.f_map({"start": "nem-start"}, [{"f": "start"}])
    h = invocations(gt.quick(g))
    assert h[0]["f"] == "nem-start"


def test_time_limit():
    # delay 1s between ops; time-limit 3s -> ops at 0,1,2 seconds
    g = gen.time_limit(3, gen.delay(1, gen.repeat({"f": "r"})))
    h = invocations(gt.quick(g))
    times = [o["time"] / 1e9 for o in h]
    assert times == [0.0, 1.0, 2.0]


def test_delay_spacing():
    g = gen.limit(4, gen.delay(0.5, gen.repeat({"f": "r"})))
    h = invocations(gt.quick(g))
    times = [o["time"] / 1e9 for o in h]
    assert times == [0.0, 0.5, 1.0, 1.5]


def test_stagger_rate():
    g = gen.time_limit(10, gen.stagger(1, gen.repeat({"f": "r"})))
    h = invocations(gt.quick(g))
    # ~1 op/sec for 10 seconds; random spacing in [0, 2s)
    assert 5 <= len(h) <= 20


def test_phases_barrier():
    g = gen.phases(gen.limit(4, gen.repeat({"f": "a"})),
                   gen.limit(2, gen.repeat({"f": "b"})))
    h = gt.quick(g)
    fs = [o["f"] for o in h]
    # every 'a' (invoke and completion) before any 'b'
    last_a = max(i for i, f in enumerate(fs) if f == "a")
    first_b = min(i for i, f in enumerate(fs) if f == "b")
    assert last_a < first_b


def test_then():
    g = gen.then(gen.once({"f": "b"}), gen.limit(2, gen.repeat({"f": "a"})))
    h = invocations(gt.quick(g))
    assert [o["f"] for o in h] == ["a", "a", "b"]


def test_clients_excludes_nemesis():
    g = gen.clients(gen.limit(6, gen.repeat({"f": "r"})))
    h = invocations(gt.quick(g))
    assert all(o["process"] != gen.NEMESIS for o in h)


def test_nemesis_routing():
    g = gen.nemesis(gen.limit(2, gen.repeat({"f": "break"})),
                    gen.limit(4, gen.repeat({"f": "r"})))
    h = invocations(gt.quick(g))
    by_f = {}
    for o in h:
        by_f.setdefault(o["f"], set()).add(o["process"])
    assert by_f["break"] == {gen.NEMESIS}
    assert gen.NEMESIS not in by_f["r"]


def test_each_thread():
    g = gen.clients(gen.each_thread(gen.once({"f": "hi"})))
    h = invocations(gt.quick(g))
    assert sorted(o["process"] for o in h) == [0, 1]


def test_reserve():
    test = {"concurrency": 4}
    g = gen.reserve(2, gen.limit(4, gen.repeat({"f": "w"})),
                    gen.limit(4, gen.repeat({"f": "r"})))
    with gen.fixed_rand():
        h = gt.simulate(test, gen.clients(g), gt.perfect)
    by_f = {}
    for o in h:
        if o["type"] == "invoke":
            by_f.setdefault(o["f"], set()).add(o["process"])
    assert by_f["w"] <= {0, 1}
    assert by_f["r"] <= {2, 3}


def test_until_ok():
    fails = {"n": 0}

    def completion(op):
        fails["n"] += 1
        comp = dict(op)
        comp["type"] = "fail" if fails["n"] < 3 else "ok"
        comp["time"] = op["time"] + 10
        return comp

    g = gen.until_ok(gen.repeat({"f": "w"}))
    with gen.fixed_rand():
        h = gt.simulate({"concurrency": 1}, gen.clients(g), completion)
    oks = [o for o in h if o["type"] == "ok"]
    assert len(oks) == 1
    # after the ok, no further invocations
    i_ok = h.index(oks[0])
    assert not any(o["type"] == "invoke" for o in h[i_ok + 1:])


def test_flip_flop():
    g = gen.limit(6, gen.flip_flop(gen.repeat({"f": "a"}),
                                   gen.repeat({"f": "b"})))
    h = invocations(gt.quick(g))
    assert [o["f"] for o in h] == ["a", "b", "a", "b", "a", "b"]


def test_process_limit():
    # all ops crash -> each op consumes a fresh process; limit 4 distinct
    # processes over concurrency 2
    g = gen.clients(gen.process_limit(4, gen.repeat({"f": "w"})))
    with gen.fixed_rand():
        h = gt.simulate({"concurrency": 2}, g, gt.perfect_info)
    procs = {o["process"] for o in h if o["type"] == "invoke"}
    assert len(procs) <= 4


def test_synchronize_waits():
    # one slow op on thread 0, then a synchronize barrier: the post-barrier
    # op must start only after the slow op completes
    def slow(op):
        comp = dict(op)
        comp["type"] = "ok"
        comp["time"] = op["time"] + 1000
        return comp

    g = gen.clients([gen.once({"f": "slow"}),
                     gen.synchronize(gen.once({"f": "after"}))])
    with gen.fixed_rand():
        h = gt.simulate({"concurrency": 2}, g, slow)
    slow_done = next(o for o in h if o["type"] == "ok" and o["f"] == "slow")
    after = next(o for o in h if o["type"] == "invoke"
                 and o["f"] == "after")
    assert after["time"] >= slow_done["time"]


def test_any_merges():
    g = gen.any(gen.limit(2, gen.repeat({"f": "a"})),
                gen.limit(2, gen.repeat({"f": "b"})))
    h = invocations(gt.quick(g))
    assert sorted(o["f"] for o in h) == ["a", "a", "b", "b"]


def test_validate_rejects_bad_op():
    g = gen.validate([{"f": "w", "type": "bogus"}])
    with pytest.raises(gen.InvalidOp):
        gt.quick(g)


def test_log_and_sleep_ops():
    # concurrency 1: the sleep must block the only client thread
    g = [gen.log("hello"), gen.sleep(1), {"f": "r"}]
    with gen.fixed_rand():
        h = gt.simulate({"concurrency": 1}, gen.clients(g), gt.perfect)
    assert h[0]["type"] == "log"
    assert h[1]["type"] == "sleep"
    r = next(o for o in h if o.get("f") == "r")
    assert r["time"] >= 1e9  # after the 1s sleep


def test_deterministic_with_seed():
    g = gen.time_limit(5, gen.stagger(0.5, gen.repeat({"f": "r"})))
    h1 = gt.quick(g)
    h2 = gt.quick(g)
    assert h1 == h2


def test_generation_rate():
    """Reference: >20k ops/s single-threaded generation
    (generator.clj:67-70). The simulator includes completion handling, so
    just assert we can push 20k ops through quickly."""
    import time
    g = gen.limit(20_000, gen.repeat({"f": "r"}))
    t0 = time.monotonic()
    h = gt.quick(g)
    dt = time.monotonic() - t0
    assert len(invocations(h)) == 20_000
    assert dt < 20, f"generator too slow: {20_000/dt:.0f} ops/s"

def test_cycle_advances_and_restarts():
    """cycle() drives a sequence to exhaustion and restarts it -- unlike
    repeat(), which never advances the underlying generator (the
    zookeeper-style sleep/start/sleep/stop schedule relies on this)."""
    from jepsen_tpu.generator.testing import perfect, simulate
    g = gen.limit(6, gen.cycle({"f": "a"}, {"f": "b"}, {"f": "c"}))
    hist = simulate({"nodes": ["n1"], "concurrency": 1}, g, perfect)
    fs = [o["f"] for o in hist if o["type"] == "invoke"]
    assert fs == ["a", "b", "c", "a", "b", "c"]


def test_cycle_empty_template_terminates():
    from jepsen_tpu.generator.testing import perfect, simulate
    hist = simulate({"nodes": ["n1"], "concurrency": 1},
                    gen.cycle(), perfect)
    assert hist == []
