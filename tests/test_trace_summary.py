"""tools/trace_summary.py end to end: a real (dummy-transport) run's
store directory in, human-readable phase/latency/telemetry summary out."""

import os
import subprocess
import sys

import pytest

from jepsen_tpu import store

from test_obs import _run_dummy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "trace_summary.py")


@pytest.fixture(scope="module")
def run_dir(tmp_path_factory):
    """One dummy run shared by both tests (module-scoped store)."""
    base = tmp_path_factory.mktemp("store")
    prev = store.base_dir
    store.base_dir = str(base)
    try:
        test = _run_dummy("summary-e2e")
        yield store.path(test)
    finally:
        store.base_dir = prev


def test_summarize_function(run_dir):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import trace_summary
    finally:
        sys.path.pop(0)
    out = trace_summary.summarize(run_dir)
    assert "lifecycle phases" in out
    assert "jepsen.run" in out and "run-case" in out
    assert "op latency" in out and "p50" in out
    assert "op counts" in out
    assert "interpreter.ops_completed" in out


def test_cli_end_to_end(run_dir):
    p = subprocess.run([sys.executable, TOOL, run_dir],
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stderr
    assert "jepsen.run" in p.stdout
    assert "p50" in p.stdout


def test_cli_bad_dir():
    p = subprocess.run([sys.executable, TOOL, "/nonexistent-dir-xyz"],
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 1
    assert "not a directory" in p.stderr
