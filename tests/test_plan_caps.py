"""Host-side plan/heuristic math for the device search: the memory
caps that keep big-state searches from building multi-GB step tensors
(a 9k-op FIFO probe crashed the TPU worker in the first BENCH_r04 run;
see PROFILE.md round 4)."""


from jepsen_tpu.checker import jax_wgl


def test_plan_sizes_caps_step_tensor_for_big_states():
    # the crash shape: C=512 (bucketed), S=8192 padded queue state
    B, W, O, T = jax_wgl._plan_sizes(16384, 8192, 512)
    # W*C*S bounded ~<=2x the 64M-element target (W buckets up to a
    # power of two, at most doubling past the cap)
    assert W * 512 * 8192 <= 2 * (64 << 20)
    assert W >= 8

    # small states keep the old throughput-oriented plan
    B2, W2, O2, T2 = jax_wgl._plan_sizes(16384, 8, 64)
    assert W2 == 512                       # 32768 // 64, unchanged


def test_plan_sizes_explicit_width_honored():
    _, W, _, _ = jax_wgl._plan_sizes(1024, 8192, 512,
                                     frontier_width=64)
    assert W == 64


def test_batch_narrowing_never_raises_capped_width(monkeypatch):
    """keyshard's per-key narrowing must not re-inflate a W that
    _plan_sizes capped for big states (the max(32, ...) floor once
    rebuilt the crash tensor)."""
    import random

    from jepsen_tpu.models import cas_register_spec
    from jepsen_tpu.parallel import keyshard
    from jepsen_tpu.simulate import random_history

    seen = {}
    orig = keyshard._build_search

    def spy(step, K, n, B, S, C, A, W, O, T, G=1, R=None, NS=None,
            **kw):
        seen.setdefault("calls", []).append(
            {"K": K, "W": W, "NS": NS, "C": C, "S": S})
        return orig(step, K, n, B, S, C, A, W, O, T, G, R, NS, **kw)

    monkeypatch.setattr(keyshard, "_build_search", spy)
    rng = random.Random(1)
    pairs = [cas_register_spec.encode(
        random_history(rng, "cas-register", 4, 30, 0.05))
        for _ in range(3)]
    keyshard.check_batch_encoded(cas_register_spec, pairs)
    assert seen["calls"], "batch path never built a kernel"
    for call in seen["calls"]:
        # the batch path pins one rollout chain per key explicitly --
        # even a compacted K=1 kernel must not flip to the NS=8 regime
        assert call["NS"] == 1
        # and the step tensor respects the ~2x-bucketed cap
        assert call["W"] * call["C"] * call["S"] <= 2 * (64 << 20)


def test_rollout_disabled_when_even_one_chain_is_too_big():
    """K*NS*n*S past ~256M elements drops the rollout instead of
    building the tensor (survive > decide-fast)."""
    import jax.numpy as jnp

    def step(st, f, a, r, xp):
        return st, xp.asarray(True)

    # n=16384, S=32768: n*S = 512M elements > 256M gate
    init_carry, run_chunk = jax_wgl._build_search(
        step, 1, 16384, 512, 32768, 4, 1, 8, 1024, 1024)
    # the kernel builds (gate ran at trace level); a smoke init works
    carry = init_carry(jnp.zeros((1, 32768), jnp.int32))
    assert int(carry[jax_wgl.IDX_TOP][0]) == 1
