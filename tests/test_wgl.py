"""WGL oracle tests: golden histories + randomized cross-validation against
brute-force permutation search."""

import itertools

import numpy as np
import pytest

from jepsen_tpu import history as h
from jepsen_tpu import models as m
from jepsen_tpu.checker import wgl


def H(*rows):
    return h.parse_history_edn_like(rows)


# -- golden histories --------------------------------------------------------

def test_empty_history_valid():
    r = wgl.check_history(m.register_spec, [])
    assert r["valid"] is True


def test_sequential_register_valid():
    hist = H(("invoke", 0, "write", 1), ("ok", 0, "write", 1),
             ("invoke", 0, "read", None), ("ok", 0, "read", 1))
    assert wgl.check_history(m.register_spec, hist)["valid"] is True


def test_stale_read_invalid():
    hist = H(("invoke", 0, "write", 1), ("ok", 0, "write", 1),
             ("invoke", 0, "write", 2), ("ok", 0, "write", 2),
             ("invoke", 0, "read", None), ("ok", 0, "read", 1))
    r = wgl.check_history(m.register_spec, hist)
    assert r["valid"] is False
    assert r["op"]["f"] == "read"


def test_concurrent_reads_both_values_valid():
    # w(1) concurrent with r->nil and r->1: both orderings exist
    hist = H(("invoke", 0, "write", 1),
             ("invoke", 1, "read", None),
             ("ok", 1, "read", None),
             ("invoke", 2, "read", None),
             ("ok", 2, "read", 1),
             ("ok", 0, "write", 1))
    assert wgl.check_history(m.register_spec, hist)["valid"] is True


def test_cas_classic_valid():
    hist = H(("invoke", 0, "write", 0), ("ok", 0, "write", 0),
             ("invoke", 1, "cas", [0, 1]),
             ("invoke", 2, "cas", [0, 2]),
             ("ok", 1, "cas", [0, 1]),
             ("fail", 2, "cas", [0, 2]),
             ("invoke", 0, "read", None), ("ok", 0, "read", 1))
    assert wgl.check_history(m.cas_register_spec, hist)["valid"] is True


def test_cas_both_succeed_same_old_invalid():
    hist = H(("invoke", 0, "write", 0), ("ok", 0, "write", 0),
             ("invoke", 1, "cas", [0, 1]), ("ok", 1, "cas", [0, 1]),
             ("invoke", 2, "cas", [0, 2]), ("ok", 2, "cas", [0, 2]))
    assert wgl.check_history(m.cas_register_spec, hist)["valid"] is False


def test_info_write_may_have_happened():
    # a timed-out write must be assumed possible: later read of its value ok
    hist = H(("invoke", 0, "write", 3), ("info", 0, "write", 3),
             ("invoke", 1, "read", None), ("ok", 1, "read", 3))
    assert wgl.check_history(m.register_spec, hist)["valid"] is True


def test_info_write_may_not_have_happened():
    hist = H(("invoke", 0, "write", 1), ("ok", 0, "write", 1),
             ("invoke", 0, "write", 3), ("info", 0, "write", 3),
             ("invoke", 1, "read", None), ("ok", 1, "read", 1))
    assert wgl.check_history(m.register_spec, hist)["valid"] is True


def test_info_op_stays_concurrent_forever():
    # crashed write can linearize arbitrarily late
    hist = H(("invoke", 0, "write", 3), ("info", 0, "write", 3),
             ("invoke", 1, "write", 5), ("ok", 1, "write", 5),
             ("invoke", 1, "read", None), ("ok", 1, "read", 5),
             ("invoke", 1, "read", None), ("ok", 1, "read", 3))
    assert wgl.check_history(m.register_spec, hist)["valid"] is True


def test_realtime_order_enforced():
    # w(1) completes before w(2) invokes; read of 1 after w(2) ok is stale
    hist = H(("invoke", 0, "write", 1), ("ok", 0, "write", 1),
             ("invoke", 1, "write", 2), ("ok", 1, "write", 2),
             ("invoke", 2, "read", None), ("ok", 2, "read", 1))
    assert wgl.check_history(m.register_spec, hist)["valid"] is False


def test_mutex_double_acquire_invalid():
    hist = H(("invoke", 0, "acquire", None), ("ok", 0, "acquire", None),
             ("invoke", 1, "acquire", None), ("ok", 1, "acquire", None))
    assert wgl.check_history(m.mutex_spec, hist)["valid"] is False


def test_mutex_valid_interleaving():
    hist = H(("invoke", 0, "acquire", None), ("ok", 0, "acquire", None),
             ("invoke", 0, "release", None), ("ok", 0, "release", None),
             ("invoke", 1, "acquire", None), ("ok", 1, "acquire", None))
    assert wgl.check_history(m.mutex_spec, hist)["valid"] is True


def test_fifo_queue_order():
    hist = H(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
             ("invoke", 0, "enqueue", 2), ("ok", 0, "enqueue", 2),
             ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 2))
    assert wgl.check_history(m.fifo_queue_spec, hist)["valid"] is False
    hist2 = H(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
              ("invoke", 0, "enqueue", 2), ("ok", 0, "enqueue", 2),
              ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1))
    assert wgl.check_history(m.fifo_queue_spec, hist2)["valid"] is True


def test_unordered_queue_any_order():
    hist = H(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
             ("invoke", 0, "enqueue", 2), ("ok", 0, "enqueue", 2),
             ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 2))
    assert wgl.check_history(m.unordered_queue_spec, hist)["valid"] is True
    bad = H(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 7))
    assert wgl.check_history(m.unordered_queue_spec, bad)["valid"] is False


def test_multi_register():
    spec = m.multi_register_spec(["x", "y"])
    hist = H(("invoke", 0, "write", {"x": 1, "y": 2}),
             ("ok", 0, "write", {"x": 1, "y": 2}),
             ("invoke", 1, "read", None), ("ok", 1, "read", {"x": 1, "y": 2}))
    assert wgl.check_history(spec, hist)["valid"] is True
    bad = H(("invoke", 0, "write", {"x": 1, "y": 2}),
            ("ok", 0, "write", {"x": 1, "y": 2}),
            ("invoke", 1, "read", None), ("ok", 1, "read", {"x": 1, "y": 9}))
    assert wgl.check_history(spec, bad)["valid"] is False


# -- randomized cross-validation against brute force -------------------------

def brute_force_linearizable(spec, e, init_state):
    """Try every permutation of ops (and every subset of info ops) that
    respects real-time order. Exponential: only for tiny histories."""
    n = len(e)
    ok_rows = [i for i in range(n) if e.is_ok[i]]
    info_rows = [i for i in range(n) if not e.is_ok[i]]
    for r in range(len(info_rows) + 1):
        for included in itertools.combinations(info_rows, r):
            rows = sorted(ok_rows + list(included))
            for perm in itertools.permutations(rows):
                # real-time: if return(a) < invoke(b), a must precede b
                pos = {x: i for i, x in enumerate(perm)}
                if any(e.return_idx[a] < e.invoke_idx[b] and pos[a] > pos[b]
                       for a in rows for b in rows if a != b):
                    continue
                state = init_state
                good = True
                for i in perm:
                    state, ok = spec.step(state, e.f[i], e.args[i], e.ret[i],
                                          np)
                    if not bool(ok):
                        good = False
                        break
                    state = np.asarray(state, np.int32)
                if good:
                    return True
    return False


def random_history(rng, n_procs=3, n_ops=6, model="cas-register"):
    """Generate a small random concurrent history of register ops."""
    hist = []
    reg = {"val": None}
    open_procs = {}
    t = 0
    procs = list(range(n_procs))
    ops_left = n_ops
    while ops_left > 0 or open_procs:
        can_invoke = [p for p in procs if p not in open_procs] \
            if ops_left > 0 else []
        if can_invoke and (not open_procs or rng.random() < 0.5):
            p = can_invoke[rng.integers(len(can_invoke))]
            kind = rng.choice(["read", "write", "cas"]) \
                if model == "cas-register" else rng.choice(["read", "write"])
            if kind == "write":
                v = int(rng.integers(0, 3))
                o = h.invoke_op(p, "write", v)
            elif kind == "cas":
                o = h.invoke_op(p, "cas",
                                [int(rng.integers(0, 3)),
                                 int(rng.integers(0, 3))])
            else:
                o = h.invoke_op(p, "read", None)
            hist.append(o)
            open_procs[p] = o
            ops_left -= 1
        else:
            p = list(open_procs)[rng.integers(len(open_procs))]
            inv = open_procs.pop(p)
            roll = rng.random()
            if roll < 0.15:
                hist.append(h.info_op(p, inv["f"], inv["value"]))
            elif roll < 0.25 and inv["f"] in ("cas",):
                hist.append(h.fail_op(p, inv["f"], inv["value"]))
            else:
                # produce a completion; value possibly wrong to create
                # invalid histories
                if inv["f"] == "read":
                    v = int(rng.integers(0, 3)) if rng.random() < 0.8 else None
                    hist.append(h.ok_op(p, "read", v))
                else:
                    hist.append(h.ok_op(p, inv["f"], inv["value"]))
        t += 1
    return h.index(hist)


@pytest.mark.parametrize("seed", range(30))
def test_wgl_matches_brute_force(seed):
    rng = np.random.default_rng(seed)
    hist = random_history(rng, n_procs=3, n_ops=5)
    spec = m.cas_register_spec
    e, s0 = spec.encode(hist)
    expected = brute_force_linearizable(spec, e, s0)
    got = wgl.check_history(spec, hist)
    assert got["valid"] is expected, hist
