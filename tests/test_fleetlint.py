"""fleetlint tests: the control-plane auditor over golden
corrupted-journal fixtures (each defect class -> its FL code), the
--resume preflight gate (PL018), the CL004 journal-writer codelint
pass, the shared store journal folds, and the loopback fleet
acceptance run (clean audit, byte-deterministic artifact,
containment: the audit can never alter an outcome or exit code)."""

import dataclasses
import json
import os

import pytest

from jepsen_tpu import checker as cc
from jepsen_tpu import cli
from jepsen_tpu import client as jc
from jepsen_tpu import generator as gen
from jepsen_tpu import store
from jepsen_tpu import tests as tst
from jepsen_tpu.analysis import codelint, fleetlint, planlint
from jepsen_tpu.analysis.diagnostics import ERROR, WARNING
from jepsen_tpu.analysis.fleetmodel import CampaignModel
from jepsen_tpu.campaign import scheduler
from jepsen_tpu.campaign.journal import CampaignJournal
from jepsen_tpu.fleet import dispatch


@pytest.fixture(autouse=True)
def store_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))


def _codes(diags):
    return [d.code for d in diags]


def _error_codes(diags):
    return [d.code for d in diags if d.severity == ERROR]


# ---------------------------------------------------------------------------
# golden-journal helpers

def mk_fleet(cid, cells=("a", "b"), status="complete", **extra):
    jr = CampaignJournal(cid)
    jr.write_meta({"status": status, "mode": "fleet",
                   "cells": list(cells), "workers": ["w1"],
                   "lease-s": 60.0, "max-leases": 3, **extra})
    return jr


def grant(jr, cell, worker="w1", attempt=1, t=None):
    jr.append_event({"event": "lease", "cell": cell, "worker": worker,
                     "attempt": attempt, "lease-s": 60.0,
                     "t": t or store.local_time()})


def forfeit(jr, cell, worker="w1"):
    jr.append_event({"event": "lease-failed", "cell": cell,
                     "worker": worker, "error": "injected",
                     "t": store.local_time()})


def terminal(jr, cell, worker="w1", attempt=1, **kw):
    jr.append_cell({"cell": cell, "group": cell, "params": {},
                    "outcome": True, "valid": True, "worker": worker,
                    "attempt": attempt, **kw})


def leased_terminal(jr, cell, **kw):
    grant(jr, cell)
    terminal(jr, cell, **kw)


def clean_fleet(cid):
    jr = mk_fleet(cid)
    leased_terminal(jr, "a")
    leased_terminal(jr, "b")
    return jr


# ---------------------------------------------------------------------------
# journal well-formedness


def test_clean_synthetic_journal_has_no_findings():
    clean_fleet("clean")
    diags = fleetlint.lint_campaign("clean")
    # runs aren't on disk in this fixture: only the coverage info
    assert _codes(diags) == ["FL014"]
    assert not _error_codes(diags)


def test_fl001_duplicate_terminal_record():
    jr = clean_fleet("dup")
    terminal(jr, "a")            # terminal-guard bypassed
    diags = fleetlint.lint_campaign("dup")
    assert "FL001" in _error_codes(diags)
    assert any("cells[a]" in d.location for d in diags
               if d.code == "FL001")
    # an aborted + re-run cell is ONE terminal record, not a duplicate
    jr2 = mk_fleet("rerun", cells=["x"])
    jr2.append_cell({"cell": "x", "outcome": "aborted"})
    grant(jr2, "x")
    terminal(jr2, "x")
    assert "FL001" not in _codes(fleetlint.lint_campaign("rerun"))


def test_fl002_unplanned_cell_and_fl003_missing_terminal():
    jr = mk_fleet("plan", cells=["a", "b"])
    leased_terminal(jr, "a")
    leased_terminal(jr, "ghost")   # not in the planned set
    diags = fleetlint.lint_campaign("plan")
    assert "FL002" in _error_codes(diags)
    assert "FL003" in _error_codes(diags)   # b never landed terminal
    # an ABORTED campaign is allowed unfinished cells
    jr2 = mk_fleet("ab", cells=["a", "b"], status="aborted")
    leased_terminal(jr2, "a")
    assert "FL003" not in _codes(fleetlint.lint_campaign("ab"))


def test_fl004_second_writer_interleaving():
    jr = mk_fleet("writers", cells=["a", "b"], resumes=1)
    grant(jr, "a")
    jr.append_cell({"cell": "a", "outcome": True, "worker": "w1",
                    "attempt": 1, "writer": "hostA:1"})
    jr.append_event({"event": "lease", "cell": "b", "worker": "w1",
                     "attempt": 1, "t": store.local_time(),
                     "writer": "hostB:2"})
    # hostA appends AFTER hostB took over: two live coordinators
    jr.append_cell({"cell": "b", "outcome": True, "worker": "w1",
                    "attempt": 1, "writer": "hostA:1"})
    diags = fleetlint.lint_campaign("writers")
    assert "FL004" in _error_codes(diags)
    # contiguous handoff with a journaled resume is legal
    jr2 = mk_fleet("handoff", cells=["a", "b"], resumes=1)
    jr2.append_cell({"cell": "a", "outcome": True, "writer": "hostA:1"})
    jr2.append_cell({"cell": "b", "outcome": True, "writer": "hostB:2"})
    assert not _error_codes([d for d in
                             fleetlint.lint_campaign("handoff")
                             if d.code == "FL004"])


def test_fl004_warns_on_unexplained_writer_count():
    jr = mk_fleet("unexplained", cells=["a", "b"])   # resumes = 0
    jr.append_cell({"cell": "a", "outcome": True, "writer": "hostA:1"})
    jr.append_cell({"cell": "b", "outcome": True, "writer": "hostB:2"})
    diags = [d for d in fleetlint.lint_campaign("unexplained")
             if d.code == "FL004"]
    assert diags and all(d.severity == WARNING for d in diags)


# ---------------------------------------------------------------------------
# lease lifecycle


def test_fl005_result_without_a_lease():
    jr = mk_fleet("nolease", cells=["a"])
    terminal(jr, "a")            # no grant at all
    assert "FL005" in _error_codes(fleetlint.lint_campaign("nolease"))
    # a grant to a DIFFERENT worker doesn't cover it either
    jr2 = mk_fleet("wrongworker", cells=["a"])
    grant(jr2, "a", worker="w1")
    terminal(jr2, "a", worker="w9")
    assert "FL005" in _error_codes(
        fleetlint.lint_campaign("wrongworker"))


def test_fl006_lease_budget_overrun():
    jr = mk_fleet("budget", cells=["a"])   # max-leases 3
    for attempt in range(1, 5):
        grant(jr, "a", attempt=attempt)
        if attempt < 4:
            forfeit(jr, "a")
    terminal(jr, "a", attempt=4)
    assert "FL006" in _error_codes(fleetlint.lint_campaign("budget"))


def test_fl007_overlapping_leases_need_a_forfeit_between():
    jr = mk_fleet("overlap", cells=["a"])
    grant(jr, "a", worker="w1", attempt=1)
    grant(jr, "a", worker="w2", attempt=2)   # no forfeit between
    terminal(jr, "a", worker="w2", attempt=2)
    assert "FL007" in _error_codes(fleetlint.lint_campaign("overlap"))
    # with the forfeit journaled, the steal is legal
    jr2 = mk_fleet("steal", cells=["a"])
    grant(jr2, "a", worker="w1", attempt=1)
    forfeit(jr2, "a")
    grant(jr2, "a", worker="w2", attempt=2)
    terminal(jr2, "a", worker="w2", attempt=2)
    assert "FL007" not in _codes(fleetlint.lint_campaign("steal"))


def test_fl007_and_fl006_tolerate_a_crash_resume():
    """A coordinator killed holding a live lease can never journal
    the forfeit; the resumed session's re-grant (NEW writer) is an
    implicit forfeit, not two live leases -- and the lease budget
    counts per coordinator session (the dispatcher's attempt counter
    starts fresh on --resume)."""
    jr = mk_fleet("crashresume", cells=["a"], resumes=1)
    jr.append_event({"event": "lease", "cell": "a", "worker": "w1",
                     "attempt": 1, "t": store.local_time(),
                     "writer": "hostA:1"})
    jr.append_event({"event": "lease", "cell": "a", "worker": "w1",
                     "attempt": 2, "t": store.local_time(),
                     "writer": "hostA:1"})   # same writer, no forfeit
    # sanity: the same-writer shape IS still a violation
    assert "FL007" in _codes(fleetlint.lint_campaign("crashresume"))
    # rebuild as a crash-resume: second grant from a NEW writer
    jr2 = mk_fleet("crashresume2", cells=["a"], resumes=1)
    jr2.append_event({"event": "lease", "cell": "a", "worker": "w1",
                      "attempt": 1, "t": store.local_time(),
                      "writer": "hostA:1"})
    for attempt in (1, 2, 3):
        jr2.append_event({"event": "lease", "cell": "a",
                          "worker": "w1", "attempt": attempt,
                          "t": store.local_time(),
                          "writer": "hostB:2",
                          **({} if attempt == 1 else {})})
        if attempt < 3:
            jr2.append_event({"event": "lease-failed", "cell": "a",
                              "worker": "w1", "error": "x",
                              "t": store.local_time(),
                              "writer": "hostB:2"})
    jr2.append_cell({"cell": "a", "outcome": True, "worker": "w1",
                     "attempt": 3, "writer": "hostB:2"})
    diags = fleetlint.lint_campaign("crashresume2")
    # 4 grants total but max 3 PER SESSION (1 + 3): no FL006, and
    # the writer handoff excuses the missing forfeit: no FL007
    assert "FL006" not in _codes(diags)
    assert "FL007" not in _codes(diags)


def test_fl015_lease_extend_outside_sync():
    jr = mk_fleet("extend", cells=["a"])
    grant(jr, "a")
    jr.append_event({"event": "lease-extend", "cell": "a",
                     "worker": "w1", "ttl-s": 35.0,
                     "reason": "artifact-sync",
                     "t": store.local_time()})
    terminal(jr, "a")
    diags = [d for d in fleetlint.lint_campaign("extend")
             if d.code == "FL015"]
    assert diags and diags[0].severity == WARNING
    # an extend followed by its sync event is the legal shape
    jr.append_event({"event": "artifact-sync", "cell": "a",
                     "worker": "w1", "status": "ok", "files": 1,
                     "t": store.local_time()})
    assert "FL015" not in _codes(fleetlint.lint_campaign("extend"))


# ---------------------------------------------------------------------------
# sync consistency


def _run_dir(name="noop/t1"):
    d = os.path.join(store.base_dir, name)
    os.makedirs(d, exist_ok=True)
    return d


def test_fl008_synced_true_with_size_mismatched_mirror():
    d = _run_dir()
    with open(os.path.join(d, "results.json"), "w") as f:
        f.write('{"valid": true}')
    jr = mk_fleet("sync", cells=["a"])
    grant(jr, "a")
    jr.append_event({"event": "artifact-sync", "cell": "a",
                     "worker": "w1", "status": "ok", "files": 1,
                     "manifest": {"results.json": 999},
                     "t": store.local_time()})
    terminal(jr, "a", synced=True, path=d)
    diags = fleetlint.lint_campaign("sync")
    assert "FL008" in _error_codes(diags)
    assert any("999" in d_.message for d_ in diags
               if d_.code == "FL008")
    # fix the manifest: clean
    jr2 = mk_fleet("sync2", cells=["a"])
    grant(jr2, "a")
    jr2.append_event({"event": "artifact-sync", "cell": "a",
                      "worker": "w1", "status": "ok", "files": 1,
                      "manifest": {"results.json":
                                   os.path.getsize(
                                       os.path.join(d,
                                                    "results.json"))},
                      "t": store.local_time()})
    terminal(jr2, "a", synced=True, path=d)
    assert "FL008" not in _codes(fleetlint.lint_campaign("sync2"))


def test_fl008_synced_true_without_event_or_dir():
    jr = mk_fleet("noevent", cells=["a"])
    grant(jr, "a")
    terminal(jr, "a", synced=True, path=_run_dir("noop/t2"))
    assert "FL008" in _error_codes(fleetlint.lint_campaign("noevent"))
    jr2 = mk_fleet("nodir", cells=["a"])
    grant(jr2, "a")
    jr2.append_event({"event": "artifact-sync", "cell": "a",
                      "worker": "w1", "status": "ok", "files": 1,
                      "t": store.local_time()})
    terminal(jr2, "a", synced=True,
             path=os.path.join(store.base_dir, "noop", "missing"))
    assert "FL008" in _error_codes(fleetlint.lint_campaign("nodir"))


def test_fl009_sync_tmp_residue():
    clean_fleet("tmpres")
    staged = store.sync_tmp_path("123-456")
    os.makedirs(staged)
    with open(os.path.join(staged, "partial"), "w") as f:
        f.write("torn")
    assert "FL009" in _error_codes(fleetlint.lint_campaign("tmpres"))


# ---------------------------------------------------------------------------
# trace causality


def _write_trace(run_dir, epoch_s, context, events=(), finalized=True):
    meta = {"name": "trace_meta", "ph": "i", "cat": "__metadata",
            "ts": 0.0, "pid": 1, "tid": 0, "s": "g",
            "args": {"epoch_ns": int(epoch_s * 1e9),
                     "context": context}}
    name = "trace.jsonl" if finalized else store.TRACE_JOURNAL_FILE
    with open(os.path.join(run_dir, name), "w") as f:
        for ev in (meta,) + tuple(events):
            f.write(json.dumps(ev) + "\n")


def _span(name, ts_us, dur_us):
    return {"name": name, "ph": "X", "cat": "lifecycle", "ts": ts_us,
            "dur": dur_us, "pid": 1, "tid": 1}


def _fleet_with_run(cid, epoch_s, context=None, events=(),
                    clock=None, finalized=True):
    d = _run_dir(f"noop/{cid}")
    _write_trace(d, epoch_s,
                 context if context is not None
                 else {"campaign": cid, "cell": "a", "worker": "w1"},
                 events, finalized=finalized)
    jr = mk_fleet(cid, cells=["a"])
    grant(jr, "a")
    terminal(jr, "a", path=d,
             clock=clock or {"worker-result-epoch": epoch_s + 100,
                             "coord-received-epoch": epoch_s + 100})
    return jr


def test_fl010_worker_span_before_its_lease_grant():
    """THE golden causality fixture: a run trace whose wall anchor
    places jepsen.run an hour before the lease grant, under a
    recovered clock offset of ~0 (the handshake stamps agree)."""
    import time
    now = time.time()
    _fleet_with_run("early", epoch_s=now - 3600,
                    events=(_span("jepsen.run", 0.0, 1e6),),
                    clock={"worker-result-epoch": now,
                           "coord-received-epoch": now})
    diags = fleetlint.lint_campaign("early")
    assert "FL010" in _error_codes(diags)
    assert any("before its lease grant" in d.message for d in diags
               if d.code == "FL010")


def test_fl010_clean_when_span_sits_inside_the_lease():
    import time
    now = time.time()
    _fleet_with_run("intime", epoch_s=now + 1.0,
                    events=(_span("jepsen.run", 0.0, 2e6),),
                    clock={"worker-result-epoch": now + 4.0,
                           "coord-received-epoch": now + 4.0})
    assert "FL010" not in _codes(fleetlint.lint_campaign("intime"))


def test_fl010_span_closing_after_the_result_stamp():
    import time
    now = time.time()
    # the run span runs 60 s on the worker's OWN clock, but the
    # worker claims it printed its result 2 s in: exec ≺ result broken
    _fleet_with_run("lateclose", epoch_s=now,
                    events=(_span("jepsen.run", 0.0, 60e6),),
                    clock={"worker-result-epoch": now + 2.0,
                           "coord-received-epoch": now + 2.0})
    diags = [d for d in fleetlint.lint_campaign("lateclose")
             if d.code == "FL010"]
    assert diags and any("after the worker printed" in d.message
                         for d in diags)


def test_fl011_unbalanced_async_spans_in_finalized_trace():
    import time
    now = time.time()
    open_ev = {"name": "nemesis.window", "ph": "b", "cat": "nemesis",
               "ts": 1.0, "pid": 1, "tid": 1, "id": "w0"}
    _fleet_with_run("unbal", epoch_s=now,
                    events=(_span("jepsen.run", 0.0, 1e6), open_ev))
    diags = [d for d in fleetlint.lint_campaign("unbal")
             if d.code == "FL011"]
    assert diags and diags[0].severity == WARNING
    # the same imbalance in a CRASH JOURNAL trace is expected, not
    # flagged (a kill -9 legitimately truncates spans)
    _fleet_with_run("unbal2", epoch_s=now,
                    events=(_span("jepsen.run", 0.0, 1e6), open_ev),
                    finalized=False)
    assert "FL011" not in _codes(fleetlint.lint_campaign("unbal2"))


def test_fl012_obs_context_disagrees_with_journal():
    import time
    _fleet_with_run("ctx", epoch_s=time.time(),
                    context={"campaign": "ctx", "cell": "OTHER",
                             "worker": "w1"},
                    events=(_span("jepsen.run", 0.0, 1e6),))
    assert "FL012" in _error_codes(fleetlint.lint_campaign("ctx"))


# ---------------------------------------------------------------------------
# chaos accounting


def _write_coord_trace(cid, fault_kinds):
    evs = [{"name": "chaos.fault", "ph": "i", "cat": "chaos",
            "ts": float(i), "pid": 1, "tid": 1,
            "args": {"kind": k, "fault": "exit-255"}}
           for i, k in enumerate(fault_kinds)]
    with open(store.campaign_path(cid, "trace.jsonl"), "w") as f:
        for ev in evs:
            f.write(json.dumps(ev) + "\n")


def test_fl013_vanished_faults():
    from jepsen_tpu.fleet import chaos as fchaos
    prof = fchaos.PROFILES["flaky-exec"].with_seed(7)
    jr = mk_fleet("vanish", cells=["a"], chaos=prof.describe())
    leased_terminal(jr, "a")
    _write_coord_trace("vanish", ["execute", "execute"])
    diags = fleetlint.lint_campaign("vanish")
    assert "FL013" in _error_codes(diags)
    # with the forfeits journaled, the faults are accounted for
    jr2 = mk_fleet("accounted", cells=["a"], chaos=prof.describe())
    grant(jr2, "a", attempt=1)
    forfeit(jr2, "a")
    grant(jr2, "a", attempt=2)
    forfeit(jr2, "a")
    grant(jr2, "a", attempt=3)
    terminal(jr2, "a", attempt=3)
    _write_coord_trace("accounted", ["execute", "execute"])
    assert "FL013" not in _codes(fleetlint.lint_campaign("accounted"))


def test_fl013_scheduled_kill_without_a_steal_trail():
    from jepsen_tpu.fleet import chaos as fchaos
    prof = fchaos.PROFILES["soak"].with_seed(42)
    cells = ["a", "b"]
    (killed,) = prof.plan_kills(cells)
    jr = mk_fleet("kills", cells=cells,
                  chaos=dataclasses.asdict(prof))
    for c in cells:
        leased_terminal(jr, c)   # one grant each: the kill vanished
    diags = fleetlint.lint_campaign("kills")
    hits = [d for d in diags if d.code == "FL013"]
    assert hits and any(f"cells[{killed}]" in d.location
                        for d in hits)


# ---------------------------------------------------------------------------
# preflight subset + PL018 resume gate


def test_preflight_subset_is_well_formedness_only():
    jr = mk_fleet("pf", cells=["a"])
    grant(jr, "a", attempt=1)
    grant(jr, "a", attempt=2)    # FL007 material: NOT in the subset
    terminal(jr, "a", attempt=2)
    assert fleetlint.preflight("pf") == []
    terminal(jr, "a", attempt=2)          # duplicate terminal IS
    assert _codes(fleetlint.preflight("pf")) == ["FL001"]


def test_pl018_resume_refused_over_corrupt_journal():
    jr = CampaignJournal("corrupt")
    jr.write_meta({"status": "aborted", "cells": ["a"]})
    jr.append_cell({"cell": "a", "outcome": True})
    jr.append_cell({"cell": "a", "outcome": False})
    with pytest.raises(scheduler.CampaignError) as ei:
        scheduler.run_cells([{"id": "a", "test": {}}],
                            campaign_id="corrupt", resume=True,
                            run_fn=lambda t: t)
    assert "PL018" in str(ei.value)
    assert "cells[a]" in str(ei.value)   # fix-hint names the cell


def test_pl018_unknown_fleetlint_knob_refuses_the_fleet():
    with pytest.raises(dispatch.FleetError) as ei:
        dispatch.run_fleet([{"id": "a"}],
                           dispatch.parse_workers("local"),
                           campaign_id="knob", fleetlint="bogus")
    assert "PL018" in str(ei.value)
    # and the journal was never created: refused before any state
    assert not os.path.exists(store.campaign_path("knob",
                                                  "cells.jsonl"))


def test_pl018_knob_values():
    assert planlint.lint_fleetlint({"fleetlint": "on"}) == []
    assert planlint.lint_fleetlint({"fleetlint": "off"}) == []
    assert planlint.lint_fleetlint({}) == []
    diags = planlint.lint_fleetlint({"fleetlint": "strict"})
    assert _codes(diags) == ["PL018"]


# ---------------------------------------------------------------------------
# codelint CL004: the journal single-writer invariant at source level


def test_cl004_flags_journal_calls_outside_the_coordinator():
    src = ("def f(jr, rec):\n"
           "    jr.append_cell(rec)\n"
           "    jr.append_event(rec)\n")
    diags = codelint.lint_source(src, filename="fleet/sync.py",
                                 journal_calls=True)
    assert _codes(diags) == ["CL004", "CL004"]
    # the pragma escapes, statement-line or block-above
    src_ok = ("def f(jr, rec):\n"
              "    # replaying a foreign journal on purpose\n"
              "    # codelint: ok -- test fixture builder\n"
              "    jr.append_cell(rec)\n"
              "    jr.append_event(rec)  # codelint: ok\n")
    assert codelint.lint_source(src_ok, filename="x.py",
                                journal_calls=True) == []
    # off by default (direct lint_source callers opt in)
    assert codelint.lint_source(src, filename="x.py") == []


def test_cl004_repo_is_clean_and_coordinators_are_exempt():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(
        codelint.__file__)))
    pkg = os.path.join(repo)
    diags = codelint.lint_paths([pkg], package_root=pkg)
    cl4 = [d for d in diags if d.code == "CL004"]
    assert cl4 == [], [str(d) for d in cl4]


# ---------------------------------------------------------------------------
# store: one shared parsed-records read


def test_store_folds_accept_preparsed_records():
    clean_fleet("folds")
    records = store.load_campaign_records("folds")
    assert store.latest_campaign_records("folds", records=records) \
        == store.latest_campaign_records("folds")
    assert store.campaign_events("folds", records=records) \
        == store.campaign_events("folds")
    assert store.fold_latest_records(records) \
        == store.latest_campaign_records("folds")
    # fleetlint's model folds from the same single read
    model = CampaignModel("folds", records=records)
    assert model.records == records
    assert model.latest == store.fold_latest_records(records)


def test_journal_records_carry_this_process_writer():
    jr = clean_fleet("stamped")
    recs = store.load_campaign_records("stamped")
    assert all(r.get("writer") == jr.writer for r in recs)
    assert str(os.getpid()) in jr.writer


# ---------------------------------------------------------------------------
# audit artifact: persistence, determinism, containment


def test_audit_persists_byte_deterministic_report():
    clean_fleet("det")
    report1, _diags = fleetlint.audit("det")
    p = store.campaign_path("det", fleetlint.ANALYSIS_FILE)
    assert report1["path"] == p
    with open(p, "rb") as f:
        b1 = f.read()
    report2, _d = fleetlint.audit("det")
    with open(p, "rb") as f:
        b2 = f.read()
    assert b1 == b2
    loaded = fleetlint.load_report("det")
    assert loaded["counts"] == report1["counts"]
    assert loaded["checks"]["records"] == 4


def test_audit_unknown_campaign_raises():
    with pytest.raises(FileNotFoundError):
        fleetlint.audit("never-existed")


def test_web_campaigns_page_shows_audit_verdict():
    from jepsen_tpu import web
    jr = clean_fleet("webaudit")
    terminal(jr, "a")            # corrupt it: FL001
    fleetlint.audit("webaudit")
    page = web._campaigns_page()
    assert "audit:" in page
    assert "1 error(s)" in page
    assert "fleet_analysis.json" in page
    # a clean campaign renders "clean"
    clean_fleet("webclean")
    fleetlint.audit("webclean")
    assert "clean" in web._campaigns_page()


class OkClient(jc.Client):
    def open(self, test, node):
        return self

    def invoke(self, test, op):
        return dict(op, type="ok")


def quick_cell(name):
    t = tst.noop_test()
    t.update(name=name, nodes=["n1"], concurrency=1,
             client=OkClient(), checker=cc.noop(),
             generator=gen.clients(
                 gen.limit(3, gen.repeat({"f": "read"}))))
    t["ssh"] = {"dummy?": True}
    t["obs?"] = False
    return t


def test_run_cells_fleetlint_off_skips_gate_and_audit():
    """The documented escape hatch: --fleetlint off must skip BOTH
    the resume preflight refusal and the finalize audit on the local
    scheduler path too."""
    jr = CampaignJournal("hatch")
    jr.write_meta({"status": "aborted", "cells": ["a"]})
    jr.append_cell({"cell": "a", "outcome": True})
    jr.append_cell({"cell": "a", "outcome": False})   # corrupt
    report = scheduler.run_cells(
        [{"id": "a", "test": quick_cell("hatch-a")}],
        campaign_id="hatch", resume=True, fleetlint=False,
        run_fn=lambda t: {**t, "results": {"valid": True}})
    assert report["status"] == "complete"
    assert "fleet_analysis" not in report
    assert fleetlint.load_report("hatch") is None


def test_containment_audit_crash_never_breaks_the_campaign(
        monkeypatch):
    def boom(*a, **kw):
        raise RuntimeError("auditor bug")

    monkeypatch.setattr(fleetlint, "audit", boom)
    report = scheduler.run_cells(
        [{"id": "a", "test": quick_cell("cont-a")}],
        campaign_id="contained")
    assert report["status"] == "complete"
    assert report["summary"]["outcomes"] == {"True": 1}
    assert "fleet_analysis" not in report


def test_containment_audit_errors_never_flip_outcomes_or_exit(
        monkeypatch):
    """THE containment acceptance: an audit full of errors is
    reported, while every cell outcome and the campaign exit code
    stay exactly what the checkers decided."""
    real = fleetlint._lint_model

    def with_injected_error(model):
        diags, checks = real(model)
        from jepsen_tpu.analysis.diagnostics import diag
        diags = diags + [diag("FL001", ERROR, "injected", "x")]
        return diags, checks

    monkeypatch.setattr(fleetlint, "_lint_model", with_injected_error)
    report = scheduler.run_cells(
        [{"id": "a", "test": quick_cell("flip-a")}],
        campaign_id="noflips")
    assert report["summary"]["outcomes"] == {"True": 1}
    assert report["status"] == "complete"
    assert report["fleet_analysis"]["counts"]["error"] >= 1
    assert cli.campaign_exit_code(report) == 0
    recs = store.latest_campaign_records("noflips")
    assert [r["outcome"] for r in recs] == [True]


# ---------------------------------------------------------------------------
# the loopback fleet acceptance: a real campaign audits clean

NOOP_OPTS = {"nodes": ["n1"], "concurrency": 1, "ssh": {"dummy?": True},
             "time-limit": 1, "workload": "noop"}


def test_loopback_fleet_audits_clean_and_deterministic():
    from jepsen_tpu.campaign import plan
    cells = plan.expand({"axes": {"seed": [0, 1],
                                  "workload": ["noop"]}})
    rep = dispatch.run_fleet(
        cells, dispatch.parse_workers("local,local"),
        campaign_id="audited", base_options=NOOP_OPTS, lease_s=120,
        builder="jepsen_tpu.demo:demo_test")
    assert rep["status"] == "complete"
    assert rep["summary"]["outcomes"] == {"True": 2}
    # the finalize audit ran, found nothing, and reported coverage
    fa = rep["fleet_analysis"]
    assert fa["counts"] == {"error": 0, "warning": 0, "info": 0}, fa
    assert fa["checks"]["runs_audited"] == 2, fa
    assert fa["checks"]["leases"] >= 2
    p = store.campaign_path("audited", fleetlint.ANALYSIS_FILE)
    assert os.path.exists(p)
    with open(p, "rb") as f:
        b1 = f.read()
    # re-auditing the same artifacts is byte-identical
    fleetlint.audit("audited")
    with open(p, "rb") as f:
        b2 = f.read()
    assert b1 == b2
    # grant ≺ exec really was checked (run traces were loaded)
    model = CampaignModel("audited")
    assert model.mode == "fleet"
    assert len(model.grants()) >= 2
    # the journal has exactly one writer: this coordinator
    writers = {r[0] for r in model.writer_runs()}
    assert len(writers) == 1
