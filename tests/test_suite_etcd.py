"""Exemplar etcd suite tests: the full consumer pipeline (CLI -> test
map -> core.run -> checkers -> store) in stub mode, plus DB command
streams against the dummy remote (reference integration level,
core_test.clj:62-120; suite shape zookeeper.clj:106-137)."""

import os
import random

import pytest

from jepsen_tpu import control as c
from jepsen_tpu import store
from jepsen_tpu.suites import etcd


@pytest.fixture(autouse=True)
def store_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))


def _opts(**kw):
    opts = {"nodes": ["n1", "n2", "n3"], "stub": True,
            "concurrency": 12, "time-limit": 3,
            "name": None}
    opts.update(kw)
    return opts


def test_register_workload_stub_end_to_end():
    random.seed(45100)
    from jepsen_tpu import core
    test = etcd.etcd_test(_opts(workload="register"))
    done = core.run(test)
    res = done["results"]
    assert res["valid"] is True
    assert res["workload"]["valid"] in (True, "unknown")
    # per-key device checking happened over real keyed subhistories
    assert any(o for o in done["history"]
               if o.get("f") in ("read", "write", "cas"))
    d = store.path(done)
    assert os.path.exists(os.path.join(d, "results.json"))
    assert os.path.exists(os.path.join(d, "timeline.html"))


def test_set_workload_stub_end_to_end():
    random.seed(45100)
    from jepsen_tpu import core
    test = etcd.etcd_test(_opts(workload="set", **{"op-count": 30}))
    done = core.run(test)
    res = done["results"]
    assert res["workload"]["valid"] is True
    # every acknowledged add was observed by the final read
    assert res["workload"]["lost-count"] == 0


def test_partition_nemesis_stub_commands():
    random.seed(45100)
    from jepsen_tpu import core
    test = etcd.etcd_test(_opts(workload="register",
                                nemesis=["partition"],
                                **{"nemesis-interval": 0.5,
                                   "time-limit": 3}))
    done = core.run(test)
    cmds = [cmd for _, cmd in done.get("dummy-log", [])]
    assert any("iptables" in x for x in cmds)
    nem_fs = {o["f"] for o in done["history"]
              if o.get("process") == "nemesis"}
    assert "start-partition" in nem_fs
    # the final generator healed the network at the end
    assert "stop-partition" in nem_fs


def test_db_setup_command_stream():
    """EtcdDB.setup against the dummy remote issues the install + daemon
    incantation (zookeeper.clj:44-60 analogue)."""
    test = {"nodes": ["n1", "n2"], "ssh": {"dummy?": True}}
    db = etcd.EtcdDB()
    with c.ssh_scope(test), c.on("n1"):
        db.start(test, "n1")
        db.kill(test, "n1")
        db.pause(test, "n1")
        db.resume(test, "n1")
    cmds = [cmd for _, cmd in test["dummy-log"]]
    started = [x for x in cmds if "daemon" in x or "etcd" in x]
    assert any("--initial-cluster" in x and
               "n1=http://n1:2380,n2=http://n2:2380" in x for x in cmds)
    assert any("start-stop-daemon" in x or "nohup" in x or "setsid" in x
               for x in started) or any("etcd" in x for x in started)
    assert any("STOP" in x for x in cmds) and any("CONT" in x
                                                  for x in cmds)


def test_cli_main_stub(capsys):
    random.seed(45100)
    with pytest.raises(SystemExit) as exc:
        etcd.main(["test", "--stub", "--node", "n1", "--node", "n2",
                   "--workload", "register", "--time-limit", "2",
                   "--concurrency", "8"])
    assert exc.value.code == 0    # valid run exits 0 (cli.clj:129-139)
    latest = store.latest()
    assert latest is not None
    assert latest["results"]["valid"] is True


def test_all_tests_matrix():
    tests = etcd.all_tests(_opts())
    names = [t["name"] for t in tests]
    assert len(tests) == 2 * (1 + len(etcd.NEMESES))
    assert "etcd-register" in names and "etcd-set" in names


def test_quickstart_default_concurrency_works():
    """The documented two-node quickstart must not crash on the register
    workload's thread-grouping requirement."""
    random.seed(45100)
    with pytest.raises(SystemExit) as exc:
        etcd.main(["test", "--stub", "--node", "n1", "--node", "n2",
                   "--time-limit", "2"])
    assert exc.value.code == 0


def test_stub_create_is_atomic():
    cl = etcd.StubRegisterClient()
    from jepsen_tpu.independent import tuple_ as T
    a = cl.open({}, "n1")
    assert a.invoke({}, {"f": "create", "value": T(0, "x")})["type"] == "ok"
    assert a.invoke({}, {"f": "create", "value": T(0, "y")})["type"] == "fail"
    assert a.invoke({}, {"f": "read", "value": T(0, None)})["value"][1] == "x"


class FakeEtcdV3:
    """In-process v3 gRPC-gateway emulation over a dict: range/put/txn
    with base64 keys and protobuf-JSON omit-default responses (absent
    "succeeded"/"kvs" when false/empty), served over real HTTP so the
    client's request construction and response parsing run live."""

    def __init__(self):
        import base64
        import http.server
        import json as _json
        import threading
        kv, lock = {}, threading.Lock()
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                body = _json.loads(self.rfile.read(
                    int(self.headers["Content-Length"])))
                d = base64.b64decode
                out = {}
                with lock:
                    if self.path == "/v3/kv/put":
                        kv[d(body["key"])] = d(body["value"])
                    elif self.path == "/v3/kv/range":
                        v = kv.get(d(body["key"]))
                        if v is not None:
                            out["kvs"] = [{
                                "key": body["key"],
                                "value": base64.b64encode(v).decode()}]
                            out["count"] = "1"
                    elif self.path == "/v3/kv/txn":
                        cmp_ = body["compare"][0]
                        key = d(cmp_["key"])
                        if cmp_["target"] == "VERSION":
                            ok = (cmp_["version"] == "0") == (
                                key not in kv)
                        else:
                            ok = kv.get(key) == d(cmp_.get("value", ""))
                        if ok:
                            put = body["success"][0]["requestPut"]
                            kv[d(put["key"])] = d(put["value"])
                            out["succeeded"] = True
                    else:
                        self.send_error(404)
                        return
                payload = _json.dumps(out).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self.httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.kv = kv

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_v3_client_against_gateway(monkeypatch):
    """The v3 client round-trips reads/writes/creates/CAS through a
    live gateway: request format and omit-default response parsing are
    pinned by actual HTTP traffic, not by reading the code."""
    from jepsen_tpu.independent import tuple_ as T
    srv = FakeEtcdV3()
    try:
        monkeypatch.setattr(etcd, "CLIENT_PORT", srv.port)
        cl = etcd.EtcdRegisterClient().open({}, "127.0.0.1")

        def run(f, value):
            return cl.invoke({}, {"type": "invoke", "f": f,
                                  "value": value})

        assert run("read", T(1, None))["value"][1] is None
        assert run("write", T(1, 3))["type"] == "ok"
        assert run("read", T(1, None))["value"][1] == 3
        # create-if-absent: taken key fails, fresh key succeeds
        assert run("create", T(1, 9))["type"] == "fail"
        assert run("create", T(2, 7))["type"] == "ok"
        assert run("read", T(2, None))["value"][1] == 7
        # cas: right old value wins, wrong one loses cleanly
        assert run("cas", T(1, (3, 4)))["type"] == "ok"
        assert run("cas", T(1, (3, 5)))["type"] == "fail"
        assert run("read", T(1, None))["value"][1] == 4
        # connection refused after shutdown: read fail, write info
        srv.close()
        assert run("read", T(1, None))["type"] == "fail"
        assert run("write", T(1, 0))["type"] == "info"
    finally:
        srv.close()
