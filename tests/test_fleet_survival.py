"""PR 9 fleet-survivability tests: crash-consistent artifact sync
over the control plane (fleet/sync.py), the seeded chaos profiles +
FaultyRemote fault injection (fleet/chaos.py, control/remotes.py),
service admission control (authn, budgets, shed, drain), planlint
PL016, the persistent jax compilation cache pairing, and the
chaos-soak acceptance run (every cell terminal exactly once, all
artifacts mirrored, 401/429 never disturbing in-flight work)."""

import contextlib
import json
import os
import shlex
import signal
import subprocess
import threading
import time
import urllib.error
import urllib.request

import pytest

from jepsen_tpu import robust, store, web
from jepsen_tpu.analysis import planlint
from jepsen_tpu.campaign import compile_cache, plan
from jepsen_tpu.campaign.journal import CampaignJournal
from jepsen_tpu.control import remotes
from jepsen_tpu.fleet import chaos as fchaos
from jepsen_tpu.fleet import dispatch, ledger as fledger, service
from jepsen_tpu.fleet import sync as fsync
from jepsen_tpu.robust import RetryPolicy


@pytest.fixture(autouse=True)
def store_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))
    compile_cache.reset()
    service.reset()
    fsync.clear_pending()
    yield
    compile_cache.reset()
    service.reset()
    fsync.clear_pending()


def _local_conn():
    return remotes.LocalRemote().connect({"host": "local"})


def _seed_run_dir(root, name="demo-noop/20260101T000000.000000+0000"):
    """A fake completed run directory with a few artifacts."""
    d = os.path.join(str(root), name)
    os.makedirs(d)
    with open(os.path.join(d, "results.json"), "w") as f:
        json.dump({"valid": True}, f)
    with open(os.path.join(d, "history.jsonl"), "w") as f:
        f.write('{"type": "invoke"}\n' * 200)
    with open(os.path.join(d, "jepsen.log"), "w") as f:
        f.write("fin\n")
    return d, name


# ---------------------------------------------------------------------------
# robust primitives: bounded retry policy, lease extension


def test_retry_policy_bounded_fits_the_budget():
    p = RetryPolicy.bounded(2.0)
    assert p.max_elapsed_s == 2.0
    assert p.tries >= 1
    t0 = time.monotonic()
    with pytest.raises(ValueError):
        p.call(lambda: (_ for _ in ()).throw(ValueError("nope")),
               retry_on_exception=ValueError, site="test.bounded")
    assert time.monotonic() - t0 < 4.0
    # degenerate budgets still give a usable policy
    assert RetryPolicy.bounded(0).max_elapsed_s > 0
    assert RetryPolicy.bounded(60, tries=0).tries == 1


def test_lease_extend_current_and_stale():
    table = robust.LeaseTable()
    lease = table.grant("cell", "w1", 1.0)
    old_deadline = lease.deadline
    assert table.extend(lease, 30.0) is True
    assert lease.deadline > old_deadline
    assert lease.ttl_s == 30.0
    # a superseding grant makes the old lease stale: extending it
    # must NOT touch the new holder's clock
    lease2 = table.grant("cell", "w2", 1.0)
    assert table.extend(lease, 99.0) is False
    assert table.release(lease2) is True


# ---------------------------------------------------------------------------
# chaos profiles: parsing, determinism, caps


def test_chaos_parse_specs():
    assert fchaos.parse(None) is None
    p = fchaos.parse("soak")
    assert p.name == "soak" and p.seed == 0
    p = fchaos.parse("soak:42")
    assert p.seed == 42
    assert fchaos.parse(p) is p
    with pytest.raises(ValueError, match="unknown chaos profile"):
        fchaos.parse("cyclone")
    with pytest.raises(ValueError, match="seed"):
        fchaos.parse("soak:abc")


def test_chaos_schedule_is_deterministic_per_worker():
    prof = fchaos.PROFILES["soak"].with_seed(7)

    def schedule(worker, n=60):
        faults = prof.faults_for(worker)
        return [faults("execute") for _ in range(n)] + \
               [faults("download") for _ in range(n)]

    assert schedule("w1") == schedule("w1")
    # at least one injected fault, and caps respected per worker
    seq = schedule("w1")
    injected = [f for f in seq if f is not None]
    assert injected
    assert sum(1 for f in seq if f == "exit-255") \
        <= prof.exec_exit255_max
    assert sum(1 for f in seq
               if isinstance(f, tuple) and f[0] == "hang") \
        <= prof.hang_max
    assert sum(1 for f in seq if f == "partial") \
        <= prof.download_partial_max


def test_chaos_plan_kills_deterministic_and_capped():
    prof = fchaos.ChaosProfile(name="k", seed=3, kills=2)
    cells = [f"c{i}" for i in range(5)]
    k1 = prof.plan_kills(cells)
    assert k1 == prof.plan_kills(list(reversed(cells)))
    assert len(k1) == 2 and k1 <= set(cells)
    assert fchaos.ChaosProfile(kills=0).plan_kills(cells) == set()
    # more kills than cells: capped, not an error
    assert len(fchaos.ChaosProfile(seed=1, kills=99)
               .plan_kills(cells)) == 5


def test_faulty_remote_exec_faults():
    seq = iter(["exit-255", None, "timeout"])
    conn = remotes.FaultyRemote(
        _local_conn(), lambda kind: next(seq, None))
    r = conn.execute({}, {"cmd": "echo hi"})
    assert r["exit"] == 255
    assert remotes.transport_failed(r)
    r = conn.execute({}, {"cmd": "echo hi"})
    assert r["exit"] == 0 and r["out"].strip() == "hi"
    r = conn.execute({}, {"cmd": "echo hi"})
    assert r["exit"] == -1 and r["err"] == "timeout"


def test_faulty_remote_hang_is_bounded_by_ctx_timeout():
    conn = remotes.FaultyRemote(
        _local_conn(), lambda kind: ("hang", 30.0))
    t0 = time.monotonic()
    r = conn.execute({"timeout": 0.2}, {"cmd": "echo hi"})
    assert time.monotonic() - t0 < 5.0
    assert r["exit"] == -1 and r["err"] == "timeout"


def test_faulty_remote_partial_download_truncates_largest(tmp_path):
    src, _ = _seed_run_dir(tmp_path / "remote")
    faults = iter(["partial"])
    conn = remotes.FaultyRemote(
        _local_conn(), lambda kind: next(faults, None))
    dest = str(tmp_path / "copy")
    r = conn.download({}, src, dest)
    assert r["exit"] == 0          # the torn copy REPORTS success
    got = os.path.getsize(os.path.join(dest, "history.jsonl"))
    want = os.path.getsize(os.path.join(src, "history.jsonl"))
    assert got == want // 2


# ---------------------------------------------------------------------------
# artifact sync: manifest, atomicity, partial detection, on-demand


def test_manifest_lists_files_and_rejects_empty(tmp_path):
    src, _ = _seed_run_dir(tmp_path / "remote")
    man = fsync.manifest(_local_conn(), src)
    assert set(man) == {"results.json", "history.jsonl", "jepsen.log"}
    assert man["history.jsonl"] == os.path.getsize(
        os.path.join(src, "history.jsonl"))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(fsync.SyncError, match="empty manifest"):
        fsync.manifest(_local_conn(), str(empty))


def test_pull_run_mirrors_atomically(tmp_path):
    src, name = _seed_run_dir(tmp_path / "remote")
    dest = store.path({"name": "demo-noop",
                       "start-time": name.split("/")[1]})
    info = fsync.pull_run(_local_conn(), src, dest)
    assert info["files"] == 3 and info["attempts"] == 1
    assert os.path.isdir(dest)
    assert json.load(open(os.path.join(dest, "results.json")))["valid"]
    # idempotent: an existing mirror short-circuits
    again = fsync.pull_run(_local_conn(), src, dest)
    assert again.get("already") is True
    # no staging litter
    assert not os.path.isdir(store.sync_tmp_path()) \
        or not os.listdir(store.sync_tmp_path())


def test_pull_run_detects_partial_and_retries(tmp_path):
    """The crash-consistency core: a torn copy that reports success
    is caught by manifest verification and retried, and the partial
    copy is NEVER visible at the destination."""
    src, _ = _seed_run_dir(tmp_path / "remote")
    faults = iter(["partial"])
    conn = remotes.FaultyRemote(
        _local_conn(), lambda kind: next(faults, None))
    dest = str(tmp_path / "store" / "demo-noop" / "t1")
    info = fsync.pull_run(conn, src, dest,
                          policy=RetryPolicy(tries=3, base_s=0.01))
    assert info["attempts"] == 2       # first torn, second clean
    assert os.path.getsize(os.path.join(dest, "history.jsonl")) == \
        os.path.getsize(os.path.join(src, "history.jsonl"))


def test_pull_run_terminal_failure_leaves_no_partial(tmp_path):
    src, _ = _seed_run_dir(tmp_path / "remote")
    conn = remotes.FaultyRemote(
        _local_conn(),
        lambda kind: "partial" if kind == "download" else None)
    dest = str(tmp_path / "store" / "demo-noop" / "t2")
    with pytest.raises(fsync.SyncError, match="partial download"):
        fsync.pull_run(conn, src, dest,
                       policy=RetryPolicy(tries=2, base_s=0.01))
    assert not os.path.exists(dest)
    assert not os.path.isdir(store.sync_tmp_path()) \
        or not os.listdir(store.sync_tmp_path())


def test_fetch_on_demand_pulls_registered_runs(tmp_path):
    src, _ = _seed_run_dir(tmp_path / "wstore")
    rel = "demo-noop/t3"
    fsync.register_pending(rel, kind="local",
                           conn_spec={"host": "local"},
                           remote_dir=src)
    assert rel in fsync.pending()
    # a path INSIDE the run dir matches its registration
    assert fsync.fetch_on_demand(rel + "/results.json") is True
    dest = os.path.join(os.path.abspath(store.base_dir), rel)
    assert os.path.isdir(dest)
    assert rel not in fsync.pending()
    # unknown paths are a cheap no
    assert fsync.fetch_on_demand("demo-noop/unknown") is False


def test_web_files_fetch_on_demand(tmp_path):
    """A browsed run link resolves even when the artifacts still live
    on the worker: web's 404 path consults fleet.sync first."""
    src, _ = _seed_run_dir(tmp_path / "wstore")
    rel = "demo-noop/t4"
    fsync.register_pending(rel, kind="local",
                           conn_spec={"host": "local"},
                           remote_dir=src)
    server = web.serve({"ip": "127.0.0.1", "port": 0})
    try:
        base = f"http://127.0.0.1:{server.server_address[1]}"
        with urllib.request.urlopen(
                f"{base}/files/{rel}/results.json", timeout=60) as r:
            assert json.loads(r.read())["valid"] is True
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# dispatch + sync end to end (real loopback worker subprocesses)

NOOP_OPTS = {"nodes": ["n1"], "concurrency": 1, "ssh": {"dummy?": True},
             "time-limit": 1, "workload": "noop"}


def _noop_cells(n=2):
    return plan.expand({"axes": {"seed": list(range(n)),
                                 "workload": ["noop"]}})


def test_fleet_sync_with_isolated_worker_store(tmp_path):
    """Workers write into their OWN store; every run directory must be
    mirrored into the coordinator store and journaled."""
    wstore = str(tmp_path / "wstore")
    rep = dispatch.run_fleet(
        _noop_cells(2), dispatch.parse_workers("local,local"),
        campaign_id="sync", base_options=NOOP_OPTS, lease_s=120,
        sync_timeout_s=60, worker_store_dir=wstore,
        builder="jepsen_tpu.demo:demo_test")
    assert rep["summary"]["outcomes"] == {"True": 2}
    recs = store.latest_campaign_records("sync")
    for r in recs:
        assert r["synced"] is True
        assert r["path"].startswith(os.path.abspath(store.base_dir))
        assert os.path.isdir(r["path"])
        assert os.path.exists(os.path.join(r["path"], "results.json"))
        assert r["worker-path"].startswith(os.path.abspath(wstore))
    evs = [e for e in store.campaign_events("sync")
           if e["event"] == "artifact-sync"]
    assert len(evs) == 2
    assert all(e["status"] == "ok" and e["files"] > 0 for e in evs)
    # web run links resolve for the mirrored runs
    assert all(web._run_link(r["path"]) for r in recs)
    assert not os.path.isdir(store.sync_tmp_path()) \
        or not os.listdir(store.sync_tmp_path())


def test_fleet_sync_failure_keeps_verdict_then_resume_resyncs(
        tmp_path):
    """Terminal sync failure: the verdict is kept (synced: false),
    the run is registered for on-demand fetch, and --resume re-SYNCS
    without re-running the cell."""
    wstore = str(tmp_path / "wstore")
    # every download torn, every attempt: sync can never succeed
    broken = fchaos.ChaosProfile(
        name="torn", seed=1,
        download_partial_p=1.0, download_partial_max=10 ** 6)
    cells = _noop_cells(1)
    rep = dispatch.run_fleet(
        cells, dispatch.parse_workers("local"),
        campaign_id="resync", base_options=NOOP_OPTS, lease_s=120,
        max_leases=1, sync_timeout_s=5, worker_store_dir=wstore,
        chaos=broken, builder="jepsen_tpu.demo:demo_test")
    assert rep["summary"]["outcomes"] == {"True": 1}
    rec = store.latest_campaign_records("resync")[0]
    assert rec["synced"] is False
    assert rec["outcome"] is True          # the verdict survived
    assert not os.path.exists(rec["path"])
    assert fsync.pending()                 # web could pull it now
    failed = [e for e in store.campaign_events("resync")
              if e["event"] == "artifact-sync"
              and e["status"] == "failed"]
    assert failed
    # no partial copy anywhere in the coordinator store
    assert not os.path.isdir(store.sync_tmp_path()) \
        or not os.listdir(store.sync_tmp_path())
    # the terminal record journaled how to reach the worker's store
    assert rec["worker-kind"] == "local"
    assert rec["worker-conn"]["host"] == "local"
    # --resume with a healthy transport AND a different worker list
    # (the original worker id isn't in it): re-sync, not re-run,
    # reaching the store via the journaled conn spec
    rep2 = dispatch.run_fleet(
        cells, dispatch.parse_workers("w2=localhost"),
        campaign_id="resync", resume=True, base_options=NOOP_OPTS,
        lease_s=120, sync_timeout_s=60, worker_store_dir=wstore,
        builder="jepsen_tpu.demo:demo_test")
    assert rep2["summary"]["skipped-resumed"] == 1
    assert os.path.isdir(rec["path"])
    assert os.path.exists(os.path.join(rec["path"], "results.json"))
    ok = [e for e in store.campaign_events("resync")
          if e["event"] == "artifact-sync" and e["status"] == "ok"]
    assert len(ok) == 1
    # the cell itself ran exactly once across both invocations
    terminal = [r for r in store.load_campaign_records("resync")
                if not r.get("event")]
    assert len(terminal) == 1


def test_fleet_sync_failure_requeues_within_lease_budget(tmp_path):
    """With lease budget left, a failed sync forfeits the lease: the
    cell re-RUNS (fresh artifacts) instead of landing unsynced."""
    wstore = str(tmp_path / "wstore")
    # the first FOUR downloads fail -- the whole internal retry
    # budget of one pull (RetryPolicy.bounded tries=4), so lease 1's
    # sync fails terminally; lease 2's pull finds a clean transport
    # (or at worst one more absorbed failure) and succeeds
    state = {"left": 4}

    def faults(kind):
        if kind == "download" and state["left"] > 0:
            state["left"] -= 1
            return "exit-255"
        return None

    workers = dispatch.parse_workers("local")
    real_connect = workers[0].connect
    workers[0].connect = \
        lambda: remotes.FaultyRemote(real_connect(), faults)
    rep = dispatch.run_fleet(
        _noop_cells(1), workers,
        campaign_id="requeue", base_options=NOOP_OPTS, lease_s=120,
        max_leases=3, sync_timeout_s=3, worker_store_dir=wstore,
        builder="jepsen_tpu.demo:demo_test")
    assert rep["summary"]["outcomes"] == {"True": 1}
    rec = store.latest_campaign_records("requeue")[0]
    assert rec["synced"] is True and os.path.isdir(rec["path"])
    assert rec["attempt"] == 2
    evs = store.campaign_events("requeue")
    assert any(e["event"] == "lease-failed"
               and "artifact sync failed" in e["error"] for e in evs)
    terminal = [r for r in store.load_campaign_records("requeue")
                if not r.get("event")]
    assert len(terminal) == 1


class _KilledMidDownload:
    """A transport whose download REALLY dies by SIGKILL partway
    through copying the run directory -- a killed scp: some artifact
    files land in the staging dir, one doesn't, and the copy process
    exits -SIGKILL. The first ``times`` downloads die this way
    (enough to exhaust one pull's whole retry budget); later ones
    delegate to the clean inner transport."""

    def __init__(self, inner, times):
        self.inner = inner
        self.left = times
        self.exits = []

    def execute(self, ctx, action):
        return self.inner.execute(ctx, action)

    def upload(self, ctx, local_paths, remote_path):
        return self.inner.upload(ctx, local_paths, remote_path)

    def download(self, ctx, remote_paths, local_path):
        if self.left <= 0:
            return self.inner.download(ctx, remote_paths, local_path)
        self.left -= 1
        # a real partial copy, then a real kill -9 of the copier:
        # results.json never arrives, and $? is -SIGKILL like a
        # snuffed scp's
        p = subprocess.run(
            ["sh", "-c",
             f"cp -rp {shlex.quote(str(remote_paths))} "
             f"{shlex.quote(str(local_path))} && "
             f"rm -f {shlex.quote(str(local_path))}/results.json && "
             "kill -9 $$"])
        self.exits.append(p.returncode)
        return {"cmd": "download", "out": "", "err": "Killed",
                "exit": p.returncode}


def test_worker_killed_mid_download_no_partials_requeued(tmp_path):
    """THE crash-consistent-sync case: the worker side dies (kill -9)
    mid-artifact-download, repeatedly enough that lease 1's sync
    fails terminally. The coordinator store must never show a partial
    run directory, the cell must be re-queued, and exactly one
    terminal record must land with its artifacts mirrored."""
    wstore = str(tmp_path / "wstore")
    workers = dispatch.parse_workers("local")
    real_connect = workers[0].connect
    conns = []

    def connect():
        conns.append(_KilledMidDownload(real_connect(), times=4))
        return conns[-1]

    workers[0].connect = connect
    rep = dispatch.run_fleet(
        _noop_cells(1), workers,
        campaign_id="midkill", base_options=NOOP_OPTS, lease_s=120,
        max_leases=3, sync_timeout_s=3, worker_store_dir=wstore,
        builder="jepsen_tpu.demo:demo_test")
    assert rep["summary"]["outcomes"] == {"True": 1}
    # the kills were real: SIGKILL exits, partial copies made
    assert any(e == -signal.SIGKILL
               for c in conns for e in c.exits)
    # exactly one terminal record, artifacts mirrored
    terminal = [r for r in store.load_campaign_records("midkill")
                if not r.get("event")]
    assert len(terminal) == 1
    rec = terminal[0]
    assert rec["synced"] is True
    assert os.path.isdir(rec["path"])
    assert os.path.exists(os.path.join(rec["path"], "results.json"))
    # the cell was re-queued (lease forfeited, re-granted)
    assert rec["attempt"] >= 2
    evs = store.campaign_events("midkill")
    assert any(e["event"] == "lease-failed"
               and "artifact sync failed" in e["error"] for e in evs)
    # NO partial run directory anywhere in the coordinator store:
    # every run dir the browser can see has its results.json, and
    # the staging area is empty
    for name in store.test_names():
        for t in store.tests(name):
            assert os.path.exists(
                os.path.join(store.base_dir, name, t,
                             "results.json")), (name, t)
    assert not os.path.isdir(store.sync_tmp_path()) \
        or not os.listdir(store.sync_tmp_path())


def test_chaos_soak_acceptance(tmp_path):
    """THE acceptance run: 2 loopback workers under the seeded soak
    profile (exec exit-255, transport hang, partial download, one
    worker kill -9, torn ledger tail) with isolated worker stores.
    Every cell must land terminal exactly once with its artifacts
    mirrored, and the journal/ledger must stay well-formed."""
    wstore = str(tmp_path / "wstore")
    prof = fchaos.PROFILES["soak"].with_seed(42)
    cells = _noop_cells(2)
    # max_leases=5: the soak can stack kill -9 + hang-timeout +
    # exit-255 (3 strikes) onto ONE cell depending on which worker
    # grabs it, and the default budget of 3 would crash it -- chaos
    # soaks raise the budget (the --max-leases help says exactly this)
    rep = dispatch.run_fleet(
        cells, dispatch.parse_workers("local,local"),
        campaign_id="soak", base_options=NOOP_OPTS, lease_s=60,
        max_leases=5, sync_timeout_s=30, worker_store_dir=wstore,
        chaos=prof, builder="jepsen_tpu.demo:demo_test")
    assert rep["status"] == "complete"
    assert rep["summary"]["outcomes"] == {"True": 2}
    meta = CampaignJournal("soak").load_meta()
    assert meta["chaos"]["name"] == "soak"
    assert meta["chaos"]["seed"] == 42
    terminal = [r for r in store.load_campaign_records("soak")
                if not r.get("event")]
    per_cell = {}
    for r in terminal:
        per_cell[r["cell"]] = per_cell.get(r["cell"], 0) + 1
    assert per_cell == {c["id"]: 1 for c in cells}
    for r in terminal:
        assert r["synced"] is True and os.path.isdir(r["path"])
    # the kill -9 really fired: its die-once marker exists and at
    # least one lease was forfeited and re-granted
    kills = prof.plan_kills([c["id"] for c in cells])
    assert len(kills) == 1
    evs = store.campaign_events("soak")
    assert sum(1 for e in evs if e["event"] == "lease") > 2
    assert any(e["event"] == "lease-failed" for e in evs)
    # the chaos-torn ledger tail was tolerated
    st = fledger.Ledger(store.compile_ledger_path()).stats()
    assert st["processes"] >= 1
    # the soak is a real oracle now: the finalize audit
    # (analysis.fleetlint) replayed the journal, sync manifests, and
    # run traces and must find ZERO errors -- every injected fault
    # accounted, every lease lifecycle legal, every mirror verified
    fa = rep["fleet_analysis"]
    assert fa["counts"]["error"] == 0, fa
    assert fa["counts"]["warning"] == 0, fa
    assert fa["checks"]["runs_audited"] == 2, fa
    from jepsen_tpu.analysis import fleetlint
    assert fleetlint.load_report("soak")["counts"] == fa["counts"]


# ---------------------------------------------------------------------------
# admission control: authn, budgets, shed, drain


def test_authorize_token_forms_and_401():
    a = service.Admission(token="sekrit")
    assert a.authorize("Bearer sekrit") == "token"
    assert a.authorize("bearer sekrit") == "token"
    assert a.authorize("sekrit") == "token"
    for bad in (None, "", "Bearer nope", "Bearer sekri"):
        with pytest.raises(service.ApiError) as ei:
            a.authorize(bad)
        assert ei.value.status == 401
        assert ei.value.headers.get("WWW-Authenticate") == "Bearer"
    # named tokens map to caller identities
    a = service.Admission(tokens={"t1": "alice", "t2": "bob"})
    assert a.authorize("Bearer t2") == "bob"
    # no tokens configured: the client address is the identity
    a = service.Admission()
    assert a.authorize(None, client="10.0.0.9") == "10.0.0.9"


def test_check_slot_budget_queue_and_shed():
    a = service.Admission(budgets={"concurrent-checks": 1,
                                   "queue-depth": 1},
                          queue_wait_s=10.0)
    entered = threading.Event()
    release = threading.Event()
    got = {}

    def holder():
        with a.check_slot("c"):
            entered.set()
            release.wait(30)

    def waiter():
        try:
            with a.check_slot("c"):
                got["waiter"] = "ran"
        except service.ApiError as e:
            got["waiter"] = e.status

    t1 = threading.Thread(target=holder)
    t1.start()
    assert entered.wait(10)
    t2 = threading.Thread(target=waiter)
    t2.start()
    # t2 occupies the whole queue (depth 1): the next caller sheds
    # IMMEDIATELY as 429 + Retry-After instead of waiting
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if a.snapshot().get("c", {}).get("waiting"):
            break
        time.sleep(0.02)
    with pytest.raises(service.ApiError) as ei:
        a._admit("c", 0)
    assert ei.value.status == 429
    assert "Retry-After" in ei.value.headers
    # freeing the slot admits the queued waiter
    release.set()
    t1.join(30)
    t2.join(30)
    assert got["waiter"] == "ran"


def test_check_slot_ops_per_day_quota():
    a = service.Admission(budgets={"ops-per-day": 10})
    with a.check_slot("c", ops=8):
        pass
    with pytest.raises(service.ApiError) as ei:
        a._admit("c", 5)
    assert ei.value.status == 429
    assert "quota" in ei.value.payload["error"]
    assert int(ei.value.headers["Retry-After"]) >= 1
    # a different caller has its own quota
    with a.check_slot("other", ops=9):
        pass


def test_campaign_budget_claim_and_release():
    a = service.Admission(budgets={"campaigns": 1})
    a.campaign_slot("c")
    with pytest.raises(service.ApiError) as ei:
        a.campaign_slot("c")
    assert ei.value.status == 429
    a.campaign_done("c")
    a.campaign_slot("c")          # released slot is reusable


def test_drain_sheds_new_and_wakes_waiters():
    a = service.Admission(budgets={"concurrent-checks": 1,
                                   "queue-depth": 4},
                          queue_wait_s=30.0)
    entered = threading.Event()
    release = threading.Event()
    got = {}

    def holder():
        with a.check_slot("c"):
            entered.set()
            release.wait(30)

    def waiter():
        try:
            with a.check_slot("c"):
                got["w"] = "ran"
        except service.ApiError as e:
            got["w"] = e.status

    t1 = threading.Thread(target=holder)
    t1.start()
    assert entered.wait(10)
    t2 = threading.Thread(target=waiter)
    t2.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if a.snapshot().get("c", {}).get("waiting"):
            break
        time.sleep(0.02)
    a.drain()
    t2.join(10)
    assert got["w"] == 503        # the QUEUED waiter was woken + shed
    with pytest.raises(service.ApiError) as ei:
        a._admit("c", 0)
    assert ei.value.status == 503
    release.set()
    t1.join(30)                   # in-flight work finished untouched


def test_check_history_over_budget_does_not_touch_inflight():
    service.configure(budgets={"concurrent-checks": 1,
                               "queue-depth": 0})
    hist = [
        {"type": "invoke", "process": 0, "f": "write", "value": 1},
        {"type": "ok", "process": 0, "f": "write", "value": 1},
    ]
    gate = service.admission()
    with gate.check_slot("10.0.0.1"):
        # the same caller is over budget: clean 429
        with pytest.raises(service.ApiError) as ei:
            service.check_history({"history": hist,
                                   "model": "register",
                                   "engine": "wgl"},
                                  caller="10.0.0.1")
        assert ei.value.status == 429
        # ANOTHER caller's in-flight work is unaffected
        out = service.check_history({"history": hist,
                                     "model": "register",
                                     "engine": "wgl"},
                                    caller="10.0.0.2")
        assert out["valid"] is True
    # and after release the original caller is served again
    out = service.check_history({"history": hist, "model": "register",
                                 "engine": "wgl"}, caller="10.0.0.1")
    assert out["valid"] is True


def test_submit_campaign_releases_budget_when_done():
    service.configure(budgets={"campaigns": 1})
    cid, _meta = service.submit_campaign(
        {"axes": {"seed": [0]},
         "options": {"workload": "noop", "time-limit": 1}},
        caller="alice")
    with pytest.raises(service.ApiError) as ei:
        service.submit_campaign({"axes": {"seed": [1]}},
                                caller="alice")
    assert ei.value.status == 429
    service._campaigns[cid]["thread"].join(120)
    # the finished campaign's slot is back; the run itself completed
    cid2, _ = service.submit_campaign(
        {"axes": {"seed": [2]},
         "options": {"workload": "noop", "time-limit": 1}},
        caller="alice")
    service._campaigns[cid2]["thread"].join(120)
    assert service.campaign_status(cid)["status"] == "complete"


def test_web_serve_token_401_and_429_over_socket():
    """The wire-level acceptance: no token = 401 (WWW-Authenticate),
    over-budget = 429 + Retry-After, both as JSON."""
    server = web.serve({"ip": "127.0.0.1", "port": 0,
                        "token": "sekrit",
                        "budgets": {"campaigns": 0}})
    base = f"http://127.0.0.1:{server.server_address[1]}"
    hist = [
        {"type": "invoke", "process": 0, "f": "write", "value": 1},
        {"type": "ok", "process": 0, "f": "write", "value": 1},
    ]

    def post(path, body, token=None):
        h = {"Content-Type": "application/json"}
        if token:
            h["Authorization"] = f"Bearer {token}"
        req = urllib.request.Request(base + path,
                                     data=json.dumps(body).encode(),
                                     headers=h)
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return r.status, json.loads(r.read()), {}
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read()), dict(e.headers)

    try:
        code, body, hdrs = post(
            "/api/check", {"history": hist, "model": "register",
                           "engine": "wgl"})
        assert code == 401 and "token" in body["error"]
        assert hdrs.get("WWW-Authenticate") == "Bearer"
        code, body, _ = post(
            "/api/check", {"history": hist, "model": "register",
                           "engine": "wgl"}, token="sekrit")
        assert code == 200 and body["valid"] is True
        code, body, hdrs = post("/api/campaigns",
                                {"axes": {"seed": [0]}},
                                token="sekrit")
        assert code == 429
        assert "Retry-After" in hdrs
    finally:
        server.shutdown()


def test_web_token_gates_files_and_pages_too(tmp_path):
    """With a token configured, the HTML/file routes are protected
    like /api: the store's histories (and the on-demand scp pull a
    /files miss can trigger) are what the token guards. Browsers
    can't set headers, so ?token= works as well."""
    run, rel = _seed_run_dir(store.base_dir)
    server = web.serve({"ip": "127.0.0.1", "port": 0,
                        "token": "sekrit"})
    base = f"http://127.0.0.1:{server.server_address[1]}"

    def get(path):
        try:
            with urllib.request.urlopen(base + path, timeout=60) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    try:
        for path in ("/", "/campaigns", f"/files/{rel}/results.json"):
            code, _body = get(path)
            assert code == 401, path
        code, body = get(f"/files/{rel}/results.json?token=sekrit")
        assert code == 200 and json.loads(body)["valid"] is True
        code, _body = get("/?token=sekrit")
        assert code == 200
        code, _body = get("/?token=wrong")
        assert code == 401
        # raw-socket read: EXACTLY one response, and none of the
        # protected content after the 401 (a gate that writes the
        # error but doesn't STOP leaks the page on the same socket)
        import socket as socketlib
        s = socketlib.create_connection(
            ("127.0.0.1", server.server_address[1]), timeout=30)
        try:
            s.sendall(b"GET /files/" + rel.encode()
                      + b"/results.json HTTP/1.0\r\n\r\n")
            raw = b""
            while chunk := s.recv(65536):
                raw += chunk
        finally:
            s.close()
        assert raw.count(b"HTTP/1.") == 1, raw[:400]
        assert b"401" in raw.split(b"\r\n", 1)[0]
        assert b'"valid"' not in raw
    finally:
        server.shutdown()


def test_shutdown_drains_before_aborting():
    service.configure()
    service.shutdown(join_s=0.1)
    assert service.admission().draining
    with pytest.raises(service.ApiError) as ei:
        service.admission()._admit("c", 0)
    assert ei.value.status == 503


def test_admission_rejects_bad_budget_values():
    with pytest.raises(ValueError):
        service.Admission(budgets={"concurrent-checks": -1})
    with pytest.raises(ValueError):
        service.Admission(budgets={"queue-depth": 1.5})


def test_admission_none_budget_means_unlimited():
    """None is documented as 'off' for ops-per-day; every budget key
    must honor it instead of TypeError-ing the request path."""
    adm = service.Admission(budgets={
        "concurrent-checks": None, "queue-depth": None,
        "campaigns": None, "ops-per-day": None})
    with contextlib.ExitStack() as stack:
        for _ in range(50):
            stack.enter_context(adm.check_slot("c", ops=10 ** 9))
    for _ in range(50):
        adm.campaign_slot("c")
    for _ in range(50):
        adm.campaign_done("c")


def test_admission_prunes_idle_callers():
    """Unauthenticated callers are keyed by client address: idle
    state must be dropped, or the table grows per source IP forever."""
    adm = service.Admission()
    for i in range(100):
        with adm.check_slot(f"10.0.0.{i}"):
            pass
    adm.campaign_slot("c")
    adm.campaign_done("c")
    assert adm.snapshot() == {}
    # held state survives until released
    with adm.check_slot("held"):
        assert "held" in adm.snapshot()
    assert adm.snapshot() == {}
    # today's op spend is NOT pruned while a daily quota is on
    quota = service.Admission(budgets={"ops-per-day": 100})
    with quota.check_slot("spender", ops=60):
        pass
    assert quota.snapshot()["spender"]["ops"] == 60
    with pytest.raises(service.ApiError) as ei:
        with quota.check_slot("spender", ops=60):
            pass
    assert ei.value.status == 429


# ---------------------------------------------------------------------------
# planlint PL016


def _codes(diags, severity=None):
    return [d.code for d in diags
            if severity is None or d.severity == severity]


def test_pl016_nonloopback_serve_without_token():
    d = planlint.lint_service({"serve?": True, "serve-ip": "0.0.0.0",
                               "auth-token?": False})
    assert _codes(d, "error") == ["PL016"]
    # an UNSET bind means the 0.0.0.0 default: still an error
    d = planlint.lint_service({"serve?": True, "auth-token?": False})
    assert _codes(d, "error") == ["PL016"]
    for ok in ({"serve?": True, "serve-ip": "127.0.0.1"},
               {"serve?": True, "serve-ip": "localhost"},
               {"serve?": True, "serve-ip": "0.0.0.0",
                "auth-token?": True},
               {"serve?": False}):
        assert not planlint.lint_service(ok), ok


def test_pl016_knob_values():
    for bad in ({"budgets": {"concurrent-checks": 0}},
                {"budgets": {"queue-depth": -2}},
                {"budgets": {"ops-per-day": True}},
                {"queue-wait-s": 0},
                {"sync-timeout-s": -1},
                {"sync-timeout-s": "fast"}):
        d = planlint.lint_service(bad)
        assert _codes(d, "error") == ["PL016"], bad
    d = planlint.lint_service({"sync-timeout-s": 120, "lease-s": 60})
    assert _codes(d, "warning") == ["PL016"]
    assert not planlint.lint_service({"sync-timeout-s": 30,
                                      "lease-s": 300})
    assert not planlint.lint_service({"budgets": {
        "concurrent-checks": 4, "ops-per-day": None}})


def test_run_fleet_refuses_exposed_serve_without_token():
    with pytest.raises(dispatch.FleetError, match="PL016"):
        dispatch.run_fleet(_noop_cells(1),
                           dispatch.parse_workers("local"),
                           campaign_id="exposed",
                           base_options=NOOP_OPTS, lease_s=120,
                           serve=True, serve_ip="0.0.0.0")


# ---------------------------------------------------------------------------
# persistent jax compilation cache + cold/warm ledger stats


def test_enable_jax_cache_points_jax_at_the_store():
    import jax
    prior = jax.config.jax_compilation_cache_dir
    try:
        path = fledger.enable_jax_cache()
        assert path == os.path.abspath(
            store.compile_ledger_path(fledger.JAX_CACHE_DIR))
        assert os.path.isdir(path)
        assert jax.config.jax_compilation_cache_dir == path
        # idempotent: a second call leaves the config alone
        assert fledger.enable_jax_cache() == path
    finally:
        jax.config.update("jax_compilation_cache_dir", prior)


def test_ledger_attach_enables_jax_cache_by_default():
    import jax
    prior = jax.config.jax_compilation_cache_dir
    try:
        fledger.attach()
        want = os.path.abspath(
            store.compile_ledger_path(fledger.JAX_CACHE_DIR))
        assert jax.config.jax_compilation_cache_dir == want
    finally:
        jax.config.update("jax_compilation_cache_dir", prior)


def test_note_stats_cold_warm_wall_aggregates():
    led = fledger.attach(jax_cache=False)
    led.note_stats(2, 1, cold_wall_s=10.5, warm_wall_s=3.25)
    sibling = fledger.Ledger(led.dir)
    sibling.note_stats(4, 0, cold_wall_s=0.0, warm_wall_s=7.75)
    st = led.stats()
    assert st["hits"] == 6 and st["misses"] == 1
    assert st["cold_wall_s"] == 10.5
    assert st["warm_wall_s"] == 11.0
    # walls are optional: a bare stats event still parses
    led.note_stats(1, 1)
    assert fledger.Ledger(led.dir).stats()["hits"] == 7
