"""SSH-wire integration rig: a localhost sshd driving the REAL SSHRemote.

Where OpenSSH exists (it does not in the CI image -- these tests
self-skip there), this spins up a throwaway sshd on a high port with
generated host/client keys and runs the toystore suite through the real
ssh/scp subprocess transport: the only layer tests/test_integration_
local.py cannot cover. Mirrors the reference's docker ssh-test
(core_test.clj:122-177) on a single machine.
"""

import os
import shutil
import subprocess
import time

import pytest

SSHD = shutil.which("sshd") or (
    "/usr/sbin/sshd" if os.path.exists("/usr/sbin/sshd") else None)
HAVE_SSH = bool(SSHD and shutil.which("ssh") and shutil.which("scp")
                and shutil.which("ssh-keygen"))

pytestmark = pytest.mark.skipif(
    not HAVE_SSH, reason="no OpenSSH stack in this image "
                         "(sshd/ssh/scp/ssh-keygen required)")

PORT = 37422


@pytest.fixture
def sshd_rig(tmp_path):
    """A running sshd on 127.0.0.1:PORT with key-only auth as the
    current user; yields the test-map ssh spec for SSHRemote."""
    keydir = tmp_path / "keys"
    keydir.mkdir()
    host_key = keydir / "host_ed25519"
    user_key = keydir / "id_ed25519"
    for k in (host_key, user_key):
        subprocess.run(["ssh-keygen", "-q", "-t", "ed25519", "-N", "",
                        "-f", str(k)], check=True)
    authorized = keydir / "authorized_keys"
    authorized.write_text((user_key.with_suffix(".pub")).read_text())
    authorized.chmod(0o600)
    config = tmp_path / "sshd_config"
    config.write_text(f"""
Port {PORT}
ListenAddress 127.0.0.1
HostKey {host_key}
AuthorizedKeysFile {authorized}
PasswordAuthentication no
PubkeyAuthentication yes
StrictModes no
UsePAM no
PidFile {tmp_path}/sshd.pid
""")
    proc = subprocess.Popen([SSHD, "-D", "-f", str(config), "-e"],
                            stderr=subprocess.DEVNULL)
    try:
        deadline = time.monotonic() + 10
        import socket
        while time.monotonic() < deadline:
            try:
                socket.create_connection(("127.0.0.1", PORT), 1).close()
                break
            except OSError:
                time.sleep(0.2)
        else:
            pytest.skip("sshd did not come up")
        import getpass
        yield {"host": "127.0.0.1", "port": PORT,
               "username": getpass.getuser(),
               "private-key-path": str(user_key)}
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_ssh_remote_exec_upload_download(sshd_rig, tmp_path):
    from jepsen_tpu.control.remotes import SSHRemote
    r = SSHRemote().connect(sshd_rig)
    out = r.execute({}, {"cmd": "echo hello-$((6*7))"})
    assert out["exit"] == 0 and out["out"].strip() == "hello-42"
    src = tmp_path / "up.txt"
    src.write_text("payload")
    dst = tmp_path / "remote.txt"
    assert r.upload({}, str(src), str(dst))["exit"] == 0
    back = tmp_path / "back.txt"
    assert r.download({}, str(dst), str(back))["exit"] == 0
    assert back.read_text() == "payload"


def test_toystore_suite_over_ssh(sshd_rig, tmp_path, monkeypatch):
    """The full toystore lifecycle through the real SSH wire."""
    from jepsen_tpu import core, store
    from jepsen_tpu.control.remotes import RetryRemote, SSHRemote
    from jepsen_tpu.suites import toystore
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))

    class FixedSSH(SSHRemote):
        # every logical node dials the same localhost sshd
        def connect(self, conn_spec):
            spec = dict(sshd_rig)
            return SSHRemote(spec)

    test = toystore.toystore_test({
        "nodes": ["n1", "n2", "n3"],
        "time-limit": 5,
        "base-port": 37440,
        "scratch-dir": str(tmp_path / "nodes"),
        "nemesis-mode": "kill",
    })
    test["ssh"] = {}
    test["remote"] = RetryRemote(FixedSSH())
    test = core.run(test)
    assert test["results"]["valid"] is True, test["results"]
