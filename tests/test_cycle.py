"""Golden-history tests for the txn library and the elle-equivalent
cycle engine (reference: elle's documented anomaly taxonomy; jepsen's
cycle workloads delegate there, cycle/append.clj:11-27)."""

import numpy as np

from jepsen_tpu import txn as t
from jepsen_tpu.cycle import (RW, WR, WW, Graph, append as ap,
                              check_graph, transitive_closure, wr as wrx)
from jepsen_tpu.tests.cycle import append as ap_wl, wr as wr_wl


# -- jepsen.txn --------------------------------------------------------------

def test_ext_reads():
    assert t.ext_reads([["r", "x", 1], ["w", "x", 2],
                        ["r", "x", 2]]) == {"x": 1}
    assert t.ext_reads([["w", "x", 2], ["r", "x", 2]]) == {}
    assert t.ext_reads([["r", "y", 3]]) == {"y": 3}


def test_ext_writes():
    assert t.ext_writes([["w", "x", 1], ["w", "x", 2],
                         ["r", "y", 0]]) == {"x": 2}
    assert t.ext_writes([["r", "x", 1]]) == {}


def test_int_write_mops():
    assert t.int_write_mops([["w", "x", 1], ["w", "x", 2]]) == \
        {"x": [["w", "x", 1]]}
    assert t.int_write_mops([["w", "x", 1], ["w", "y", 2]]) == {}


def test_reduce_mops_and_op_mops():
    hist = [{"value": [["r", "x", 1], ["w", "x", 2]]},
            {"value": [["w", "y", 3]]}]
    total = t.reduce_mops(lambda s, op, mop: s + 1, 0, hist)
    assert total == 3
    assert len(list(t.op_mops(hist))) == 3


# -- graph engine ------------------------------------------------------------

def test_transitive_closure_host_vs_device():
    rng = np.random.default_rng(45100)
    n = 100   # > 64 forces the jitted repeated-squaring path
    adj = rng.random((n, n)) < 0.03
    np.fill_diagonal(adj, False)
    got = transitive_closure(adj)
    # reference: iterative host closure
    want = adj.copy()
    for _ in range(8):
        want = want | (want @ want)
    assert np.array_equal(got, want)


def test_check_graph_classifications():
    ops = [{"index": i} for i in range(3)]
    # pure ww cycle
    g = Graph(3)
    g.add(0, 1, WW)
    g.add(1, 0, WW)
    res = check_graph(g, ops)
    assert res["anomaly_types"] == ["G0"]
    # ww+wr cycle
    g = Graph(3)
    g.add(0, 1, WW)
    g.add(1, 2, WR)
    g.add(2, 0, WW)
    res = check_graph(g, ops)
    assert "G1c" in res["anomaly_types"] and "G0" not in res["anomaly_types"]
    # one rw -> G-single
    g = Graph(3)
    g.add(0, 1, RW)
    g.add(1, 0, WR)
    res = check_graph(g, ops)
    assert "G-single" in res["anomaly_types"]
    # two rw -> G2
    g = Graph(3)
    g.add(0, 1, RW)
    g.add(1, 0, RW)
    res = check_graph(g, ops)
    assert res["anomaly_types"] == ["G2"]
    # acyclic
    g = Graph(3)
    g.add(0, 1, WW)
    g.add(1, 2, RW)
    assert check_graph(g, ops)["valid"] is True


# -- list-append inference ---------------------------------------------------

def H(*txns):
    """Build ok ops from txn mop-lists (with optional type override)."""
    out = []
    for i, txn in enumerate(txns):
        typ = "ok"
        if isinstance(txn, tuple):
            typ, txn = txn
        out.append({"type": typ, "f": "txn", "process": i,
                    "time": i * 10, "index": i, "value": txn})
    return out


def test_append_valid_serial():
    hist = H([["append", "x", 1]],
             [["r", "x", [1]], ["append", "x", 2]],
             [["r", "x", [1, 2]]])
    res = ap.analyze(hist)
    assert res["valid"] is True


def test_append_g0_write_cycle():
    hist = H([["append", "x", 1], ["append", "y", 1]],
             [["append", "x", 2], ["append", "y", 2]],
             [["r", "x", [1, 2]], ["r", "y", [2, 1]]])
    res = ap.analyze(hist)
    assert "G0" in res["anomaly_types"]
    assert res["valid"] is False
    cyc = res["anomalies"]["G0"][0]
    assert all("ww" in s["type"] for s in cyc["steps"])


def test_append_g1c_wr_cycle():
    hist = H([["r", "y", [1]], ["append", "x", 1]],
             [["r", "x", [1]], ["append", "y", 1]])
    res = ap.analyze(hist)
    assert "G1c" in res["anomaly_types"]


def test_append_g_single():
    hist = H([["append", "x", 1], ["append", "y", 1]],
             [["r", "x", []], ["r", "y", [1]]],
             [["r", "x", [1]]])
    res = ap.analyze(hist)
    assert "G-single" in res["anomaly_types"]
    assert res["anomalies"]["G-single"][0]["rw_count"] == 1


def test_append_g2_write_skew():
    hist = H([["r", "x", []], ["append", "y", 1]],
             [["r", "y", []], ["append", "x", 1]],
             [["r", "x", [1]], ["r", "y", [1]]])
    res = ap.analyze(hist)
    assert "G2" in res["anomaly_types"]
    assert res["anomalies"]["G2"][0]["rw_count"] >= 2


def test_append_g1a_aborted_read():
    hist = H(("fail", [["append", "x", 9]]),
             [["r", "x", [9]]])
    res = ap.analyze(hist)
    assert "G1a" in res["anomaly_types"]


def test_append_g1b_intermediate_read():
    hist = H([["append", "x", 1], ["append", "x", 2]],
             [["r", "x", [1]]])
    res = ap.analyze(hist)
    assert "G1b" in res["anomaly_types"]


def test_append_txn_adjacency_extends_version_order():
    # T0's second append was never read, but within-txn adjacency extends
    # the version order past the longest read, so T1's read of [1] gains
    # an RW edge to T0 and the WR+RW pair classifies as G-single (on top
    # of the G1b intermediate read)
    hist = H([["append", "x", 1], ["append", "x", 2]],
             [["r", "x", [1]]])
    res = ap.analyze(hist)
    assert "G-single" in res["anomaly_types"]
    assert "G1b" in res["anomaly_types"]


def test_append_txn_adjacency_conflict_is_incompatible_order():
    # a read order that contradicts within-txn append adjacency
    hist = H([["append", "x", 1], ["append", "x", 2]],
             [["r", "x", [2, 1]]])
    res = ap.analyze(hist)
    assert "incompatible-order" in res["anomaly_types"]


def test_append_txn_adjacency_midorder_conflict():
    # T0 atomically appends [1,2]; T2 appends 3; a read observed [1,3]:
    # no serial order can put 3 between 1 and its adjacent successor 2
    hist = H([["append", "x", 1], ["append", "x", 2]],
             [["append", "x", 3]],
             [["r", "x", [1, 3]]])
    res = ap.analyze(hist)
    assert "incompatible-order" in res["anomaly_types"]


def test_append_incompatible_order():
    hist = H([["r", "x", [1, 2]]],
             [["r", "x", [2, 1]]],
             [["append", "x", 1]],
             [["append", "x", 2]])
    res = ap.analyze(hist)
    assert "incompatible-order" in res["anomaly_types"]


def test_append_duplicates():
    hist = H([["append", "x", 1]],
             [["r", "x", [1, 1]]])
    res = ap.analyze(hist)
    assert "duplicates" in res["anomaly_types"]


def test_append_garbage_read_is_unknown():
    hist = H([["r", "x", [5]]])
    res = ap.analyze(hist)
    assert res["valid"] == "unknown"


def test_append_info_append_observed_is_ok():
    hist = H(("info", [["append", "x", 1]]),
             [["r", "x", [1]]])
    res = ap.analyze(hist)
    assert res["valid"] is True


# -- wr register inference ---------------------------------------------------

def test_wr_g1c_read_cycle():
    hist = H([["r", "y", 1], ["w", "x", 1]],
             [["r", "x", 1], ["w", "y", 1]])
    res = wrx.analyze(hist)
    assert "G1c" in res["anomaly_types"]


def test_wr_g1a_and_g1b():
    hist = H(("fail", [["w", "x", 5]]),
             [["r", "x", 5]])
    assert "G1a" in wrx.analyze(hist)["anomaly_types"]
    hist = H([["w", "x", 1], ["w", "x", 2]],
             [["r", "x", 1]])
    assert "G1b" in wrx.analyze(hist)["anomaly_types"]


def test_wr_linearizable_keys_g_single():
    hist = H([["w", "x", 1]],
             [["w", "y", 2], ["w", "x", 2]],
             [["r", "y", 2], ["r", "x", 1]])
    res = wrx.analyze(hist, {"linearizable_keys": True})
    assert "G-single" in res["anomaly_types"]


def test_wr_valid():
    hist = H([["w", "x", 1]],
             [["r", "x", 1], ["w", "y", 1]],
             [["r", "y", 1]])
    res = wrx.analyze(hist, {"linearizable_keys": True})
    assert res["valid"] is True


# -- workload wrappers -------------------------------------------------------

def test_append_workload_generator_and_checker():
    import random
    random.seed(45100)
    wl = ap_wl.test({"key-count": 2, "max-writes-per-key": 4})
    g = wl["generator"]
    seen_vals = {}
    for _ in range(50):
        op = g(None, None)
        assert op["f"] == "txn"
        for mop in op["value"]:
            f, k, v = mop
            assert f in ("append", "r")
            if f == "append":
                # appends are unique per key and ascending
                assert v > seen_vals.get(k, 0)
                seen_vals[k] = v
    # checker plugs into the Checker protocol
    hist = H([["append", 0, 1]], [["r", 0, [1]]])
    res = wl["checker"].check({}, hist)
    assert res["valid"] is True


def test_wr_workload_generator():
    import random
    random.seed(45100)
    g = wr_wl.gen({"key-count": 2})
    op = g(None, None)
    assert all(m[0] in ("w", "r") for m in op["value"])


def test_check_graph_reports_g2_alongside_g_single():
    """A G-single cycle must not mask an independent write-skew (G2)
    cycle elsewhere in the graph."""
    ops = [{"index": i} for i in range(4)]
    g = Graph(4)
    g.add(0, 1, RW)
    g.add(1, 0, WR)   # G-single: 0->1 rw, 1->0 wr
    g.add(2, 3, RW)
    g.add(3, 2, RW)   # G2: pure anti-dependency cycle
    res = check_graph(g, ops)
    assert "G-single" in res["anomaly_types"]
    assert "G2" in res["anomaly_types"]


def test_wr_linearizable_keys_concurrent_writes_no_false_cycle():
    """Writes whose executions overlap in realtime must not be ordered by
    completion time (that fabricates cycles on valid histories)."""
    hist = [
        {"type": "invoke", "process": 0, "f": "txn", "time": 0,
         "value": [["w", "x", 1]]},
        {"type": "invoke", "process": 1, "f": "txn", "time": 1,
         "value": [["r", "x", None], ["w", "x", 2]]},
        {"type": "ok", "process": 1, "f": "txn", "time": 5,
         "value": [["r", "x", 1], ["w", "x", 2]]},
        {"type": "ok", "process": 0, "f": "txn", "time": 10,
         "value": [["w", "x", 1]]},
    ]
    res = wrx.check(hist, {"linearizable_keys": True})
    assert res["valid"] is True


def test_clock_package_disabled_contributes_no_nemesis():
    """faults=['kill'] must not set up the clock nemesis (no gcc install
    / ntpd stop / clock reset on nodes that only asked for kills)."""
    from jepsen_tpu import control as c
    from jepsen_tpu.nemesis import combined as nc

    class D:
        pass

    from jepsen_tpu import db as jdb

    class KDB(jdb.DB, jdb.Process):
        def setup(self, t, n): pass
        def teardown(self, t, n): pass
        def start(self, t, n): pass
        def kill(self, t, n): pass

    pkg = nc.nemesis_package({"db": KDB(), "faults": ["kill"]})
    assert not any("clock" in f for f in pkg["nemesis"].fs())
    test = {"nodes": ["n1"], "ssh": {"dummy?": True}}
    with c.ssh_scope(test):
        pkg["nemesis"].setup(test)
    cmds = [cmd for _, cmd in test.get("dummy-log", [])]
    assert not any("ntpdate" in x or "gcc" in x for x in cmds)


def test_wr_sequential_keys_detects_order_disagreement():
    """Two processes observing x's versions in opposite orders is a ww
    cycle under the sequential-keys assumption."""
    hist = H([["w", "x", 1]],
             [["w", "x", 2]],
             [["r", "x", 1]],
             [["r", "x", 2]],
             [["r", "x", 2]],
             [["r", "x", 1]])
    # processes: H assigns process=i; regroup so p4 sees 1 then 2 and
    # p5 sees 2 then 1
    hist[2]["process"] = hist[3]["process"] = 4
    hist[4]["process"] = hist[5]["process"] = 5
    res = wrx.analyze(hist, {"sequential_keys": True})
    assert res["valid"] is False
    assert "G0" in res["anomaly_types"] or "G2" in res["anomaly_types"]


def test_wr_garbage_read_unknown():
    hist = H([["r", "x", 99]])
    res = wrx.analyze(hist)
    assert res["valid"] == "unknown"
    hist = H(("info", [["w", "x", 7]]),
             [["r", "x", 7]])
    assert wrx.analyze(hist)["valid"] is True


def test_wr_sequential_keys_intra_txn_witness():
    """[r x 1][w x 2] inside one txn witnesses 1 < 2 even though the
    write overwrites the read's key."""
    hist = H([["w", "x", 1]],
             [["r", "x", 1], ["w", "x", 2]],
             [["r", "x", 2]],
             [["r", "x", 1]])
    # p4... regroup: one process reads 2 then 1, contradicting 1 < 2
    hist[2]["process"] = hist[3]["process"] = 9
    res = wrx.analyze(hist, {"sequential_keys": True})
    assert res["valid"] is False


# -- strict-serializability (realtime) classes --------------------------------

def P(*txns):
    """Paired invoke/ok history from (inv_time, ok_time, mops[, proc])
    tuples; the process defaults to the txn's position."""
    from jepsen_tpu import history as hh
    out = []
    for i, tx in enumerate(txns):
        t0, t1, mops = tx[:3]
        proc = tx[3] if len(tx) > 3 else i
        out.append({"type": "invoke", "f": "txn", "process": proc,
                    "time": t0, "value": mops})
        out.append({"type": "ok", "f": "txn", "process": proc,
                    "time": t1, "value": mops})
    return hh.index(out)


def test_append_g1c_realtime_stale_future_read():
    # T0 read x=[2] and COMPLETED before T1 (which appended 2) was even
    # invoked: WR T1->T0 plus RT T0->T1. Serializable, not strictly so.
    hist = P((0, 10, [["r", "x", [2]]]),
             (20, 30, [["append", "x", 2]]))
    res = ap.check(hist)
    assert "G1c-realtime" in res["anomaly_types"], res["anomaly_types"]
    assert res["valid"] is False


def test_append_g0_realtime_reversed_version_order():
    # a read proves 2 precedes 1 in x's order, but 1's appender ran
    # strictly before 2's: WW T1->T0 + RT T0->T1
    hist = P((0, 10, [["append", "x", 1]]),
             (20, 30, [["append", "x", 2]]),
             (40, 50, [["r", "x", [2, 1]]]))
    res = ap.check(hist)
    assert "G0-realtime" in res["anomaly_types"], res["anomaly_types"]


def test_append_g_single_realtime():
    # T2 read x=[1] -- missing 2 -- but 2's appender completed before
    # T2 was invoked: RW T2->T1 + RT T1->T2
    hist = P((0, 10, [["append", "x", 1]]),
             (20, 30, [["append", "x", 2]]),
             (40, 50, [["r", "x", [1]]]),
             (60, 70, [["r", "x", [1, 2]]]))
    res = ap.check(hist)
    assert "G-single-realtime" in res["anomaly_types"], \
        res["anomaly_types"]


def test_append_g2_realtime_write_skew_with_rt():
    # two anti-dependencies closed by ONE realtime edge (T_y completed
    # before T_a began; every other pair overlaps):
    #   T_a -rw-> T_x -rw-> T_y -rt-> T_a
    hist = P((0, 100, [["r", "z", []], ["append", "y", 1]]),    # T_y
             (90, 200, [["r", "y", []], ["append", "x", 1]]),   # T_x
             (150, 160, [["r", "x", []]]),                      # T_a
             (300, 310, [["r", "x", [1]], ["r", "y", [1]]]))    # T_r
    res = ap.check(hist)
    assert "G2-realtime" in res["anomaly_types"], res["anomaly_types"]
    assert "G-single-realtime" not in res["anomaly_types"]


def test_append_realtime_off_restores_serializable_verdict():
    hist = P((0, 10, [["r", "x", [2]]]),
             (20, 30, [["append", "x", 2]]))
    res = ap.check(hist, {"realtime": False})
    assert res["valid"] is True


def test_wr_lost_update():
    hist = P((0, 10, [["w", "x", 1]]),
             (20, 30, [["r", "x", 1], ["w", "x", 2]]),
             (20, 31, [["r", "x", 1], ["w", "x", 3]]))
    res = wrx.check(hist)
    assert "lost-update" in res["anomaly_types"], res["anomaly_types"]
    assert res["valid"] is False


def test_wr_internal():
    hist = P((0, 10, [["w", "x", 1], ["r", "x", 2]]),)
    res = wrx.check(hist)
    assert "internal" in res["anomaly_types"], res["anomaly_types"]


def test_wr_g1c_realtime():
    # read of a value written by a strictly-later txn
    hist = P((0, 10, [["r", "x", 2]]),
             (20, 30, [["w", "x", 2]]))
    res = wrx.check(hist)
    assert "G1c-realtime" in res["anomaly_types"], res["anomaly_types"]


def test_realtime_injection_fuzzer():
    """Seeded fuzzer: valid filler histories with ONE anomaly pattern
    injected must always be flagged with (at least) the injected class;
    uninjected fillers stay valid (VERDICT r2 item 5's done-condition)."""
    import random as _r

    def filler(base_t, key, vals):
        """Sequential appends + a confirming read: valid + rt-clean."""
        txns = []
        t = base_t
        for v in vals:
            txns.append((t, t + 5, [["append", key, v]]))
            t += 10
        txns.append((t, t + 5, [["r", key, list(vals)]]))
        return txns, t + 10

    classes = ["G1c-realtime", "G0-realtime", "G-single-realtime",
               "lost-update", "internal", "dirty-update",
               "G-single-process", "cyclic-versions", None]
    hits = {c: 0 for c in classes}
    for seed in range(90):
        rng = _r.Random(seed)
        cls = classes[seed % len(classes)]
        txns, t = filler(0, "f1", [1, 2, 3])
        more, t = filler(t, "f2", [1, 2])
        txns += more
        opts = None
        if cls == "G1c-realtime":
            txns += [(t, t + 5, [["r", "k", [7]]]),
                     (t + 10, t + 15, [["append", "k", 7]])]
        elif cls == "G0-realtime":
            txns += [(t, t + 5, [["append", "k", 1]]),
                     (t + 10, t + 15, [["append", "k", 2]]),
                     (t + 20, t + 25, [["r", "k", [2, 1]]])]
        elif cls == "G-single-realtime":
            txns += [(t, t + 5, [["append", "k", 1]]),
                     (t + 10, t + 15, [["append", "k", 2]]),
                     (t + 20, t + 25, [["r", "k", [1]]]),
                     (t + 30, t + 35, [["r", "k", [1, 2]]])]
        elif cls == "cyclic-versions":
            # duplicate append: cyclic within-txn adjacency, no read
            txns += [(t, t + 5, [["append", "k", 9], ["append", "k", 8],
                                 ["append", "k", 9]])]
        rng.shuffle(txns)
        if cls == "G-single-process":
            # appended AFTER the shuffle: process edges follow history
            # order, so the same-process pair must stay ordered.
            # Overlapping intervals (no rt order among the three); the
            # process appends then fails to see its own append.
            txns += [(t, t + 100, [["append", "k", 1]], 77),
                     (t + 1, t + 101, [["r", "k", [1]]], 78),
                     (t + 2, t + 102, [["r", "k", []]], 77)]
            opts = {"anomalies":
                    list(ap.DEFAULT_ANOMALIES) + ["G-single-process"]}
        if cls in ("lost-update", "internal", "dirty-update"):
            # rw-register flavor
            wtxns = [(a, b, [[("w" if m[0] == "append" else "r"),
                              m[1], m[2][-1] if isinstance(m[2], list)
                              and m[2] else (m[2] if not isinstance(
                                  m[2], list) else None)]
                             for m in mops])
                     for a, b, mops in filler(0, "g1", [1, 2])[0]]
            if cls == "lost-update":
                wtxns += [(100, 110, [["w", "k", 1]]),
                          (120, 130, [["r", "k", 1], ["w", "k", 2]]),
                          (121, 131, [["r", "k", 1], ["w", "k", 3]])]
            elif cls == "dirty-update":
                wtxns += [(100, 110, [["w", "k", 1]]),
                          (120, 130, [["r", "k", 1], ["w", "k", 2]])]
            else:
                wtxns += [(100, 110, [["w", "k", 1], ["r", "k", 9]])]
            hist = P(*wtxns)
            if cls == "dirty-update":
                # abort the injected write: its reader committed a
                # write on top of the aborted value
                for o in hist:
                    if o["type"] == "ok" \
                            and o.get("value") == [["w", "k", 1]]:
                        o["type"] = "fail"
            res = wrx.check(hist)
            assert cls in res["anomaly_types"], (seed, cls, res)
            hits[cls] += 1
            continue
        res = ap.check(P(*txns), opts)
        if cls is None:
            assert res["valid"] is True, (seed, res)
        else:
            assert cls in res["anomaly_types"], (seed, cls,
                                                 res["anomaly_types"])
            hits[cls] += 1
    assert all(v > 0 for c, v in hits.items() if c is not None)


def test_realtime_class_requires_rt_edge_in_witness():
    """A plain serializability violation must NOT masquerade as a
    *-realtime anomaly when only realtime classes are requested
    (advisor finding r3): with no rt edge in any witness cycle, the
    realtime classes stay silent."""
    from jepsen_tpu.cycle import RT, RW, WR, Graph, check_graph
    ops = [{"index": i} for i in range(4)]
    g = Graph(4)
    g.add(0, 1, RW)
    g.add(1, 0, WR)      # plain G-single cycle, no rt involved
    g.add(2, 3, RT)      # unrelated rt edge elsewhere
    res = check_graph(g, ops, anomalies=("G-single-realtime",
                                         "G2-realtime"))
    assert res["valid"] is True
    res2 = check_graph(g, ops, anomalies=("G-single",
                                          "G-single-realtime"))
    assert res2["anomaly_types"] == ["G-single"]


# -- sequential consistency (process), dirty-update, cyclic-versions --------


def test_append_g_single_process_read_own_writes_violation():
    """A process appends then fails to observe its own write: a
    serializable history (order the read first) that violates
    SEQUENTIAL consistency -- detectable only via process edges
    (VERDICT r3 missing #2; elle.core's :sequential analysis)."""
    hist = H([["append", "x", 1]],
             [["r", "x", [1]]],
             [["r", "x", []]])
    hist[0]["process"] = hist[2]["process"] = 1
    # plain + realtime classes: valid (completion-only, so no RT edges)
    assert ap.analyze(hist)["valid"] is True
    # requesting a *-process class auto-enables process edges
    res = ap.analyze(hist, anomalies=("G-single-process", "G2-process"))
    assert res["anomaly_types"] == ["G-single-process"], res
    ex = res["anomalies"]["G-single-process"][0]
    assert any("process" in s["type"].split("+") for s in ex["steps"])


def test_process_classes_off_by_default():
    hist = H([["append", "x", 1]],
             [["r", "x", [1]]],
             [["r", "x", []]])
    hist[0]["process"] = hist[2]["process"] = 1
    res = ap.check(hist)
    assert res["valid"] is True, res


def test_wr_g0_process_write_order_inversion():
    """One process's own two writes appear in the key's version order
    reversed: WW (sequential_keys) + PROC cycle."""
    hist = H([["w", "x", 1]],
             [["w", "x", 2]],
             [["r", "x", 2]],
             [["r", "x", 1]])
    # same process wrote 1 then 2...
    hist[0]["process"] = hist[1]["process"] = 5
    # ...but another process observed 2 then 1
    hist[2]["process"] = hist[3]["process"] = 9
    res = wrx.analyze(hist, {"sequential_keys": True,
                             "anomalies": ("G0-process", "G1c-process")})
    assert "G0-process" in res["anomaly_types"] \
        or "G1c-process" in res["anomaly_types"], res


def test_wr_dirty_update():
    """A committed txn read-modify-wrote on top of an ABORTED write
    (elle's dirty-update; reserved-unimplemented in round 3)."""
    hist = [
        {"type": "fail", "f": "txn", "process": 1, "time": 10,
         "index": 0, "value": [["w", "x", 1]]},
        {"type": "ok", "f": "txn", "process": 2, "time": 30,
         "index": 1, "value": [["r", "x", 1], ["w", "x", 2]]},
    ]
    res = wrx.analyze(hist)
    assert "dirty-update" in res["anomaly_types"], res
    assert "G1a" in res["anomaly_types"]        # the read itself
    assert res["valid"] is False
    w = res["anomalies"]["dirty-update"][0]
    assert w["key"] == "x" and w["aborted_value"] == 1


def test_wr_plain_read_of_aborted_write_is_not_dirty_update():
    hist = [
        {"type": "fail", "f": "txn", "process": 1, "time": 10,
         "index": 0, "value": [["w", "x", 1]]},
        {"type": "ok", "f": "txn", "process": 2, "time": 30,
         "index": 1, "value": [["r", "x", 1]]},    # read-only: G1a only
    ]
    res = wrx.analyze(hist)
    assert "G1a" in res["anomaly_types"]
    assert "dirty-update" not in res["anomaly_types"]


def test_append_cyclic_versions_duplicate_append():
    """A txn appending the same element twice makes its within-txn
    adjacency cyclic: no total version order exists (elle's
    cyclic-versions; VERDICT r3 next #5). No read ever observes the
    key, so only the adjacency source can catch it."""
    hist = H([["append", "x", 1], ["append", "x", 2],
              ["append", "x", 1]])
    res = ap.analyze(hist)
    assert "cyclic-versions" in res["anomaly_types"], res
    assert res["valid"] is False


def test_append_cyclic_versions_read_contradicts_adjacency():
    hist = H([["append", "x", 1], ["append", "x", 2]],
             [["r", "x", [2, 1]]])
    res = ap.analyze(hist)
    assert "cyclic-versions" in res["anomaly_types"], res


def test_rt_skipped_for_unknown_completion_time():
    """An ok op with NO completion time must not gain outgoing RT edges
    (advisor finding r3: treating a missing time as 0 ordered the op
    before everything and fabricated *-realtime verdicts)."""
    from jepsen_tpu import history as hh
    out = [
        {"type": "invoke", "f": "txn", "process": 0, "time": 0,
         "value": [["r", "x", [2]]]},
        {"type": "ok", "f": "txn", "process": 0,
         "value": [["r", "x", [2]]]},           # completion time unknown
        {"type": "invoke", "f": "txn", "process": 1, "time": 20,
         "value": [["append", "x", 2]]},
        {"type": "ok", "f": "txn", "process": 1, "time": 30,
         "value": [["append", "x", 2]]},
    ]
    res = ap.check(hh.index(out))
    # with a known completion (time 10 < invoke 20) this is the
    # G1c-realtime case; unknown completion must stay serializable
    assert res["valid"] is True, res


def test_rt_skipped_for_unknown_invocation_time():
    """An invoke event with NO time must not gain incoming RT edges:
    falling back to the completion time would fabricate strictness
    (the op may really have been invoked much earlier, concurrent
    with its supposed predecessor)."""
    from jepsen_tpu import history as hh
    out = [
        {"type": "invoke", "f": "txn", "process": 0, "time": 0,
         "value": [["r", "x", [2]]]},
        {"type": "ok", "f": "txn", "process": 0, "time": 10,
         "value": [["r", "x", [2]]]},
        {"type": "invoke", "f": "txn", "process": 1,
         "value": [["append", "x", 2]]},        # invoke time unknown
        {"type": "ok", "f": "txn", "process": 1, "time": 30,
         "value": [["append", "x", 2]]},
    ]
    res = ap.check(hh.index(out))
    assert res["valid"] is True, res


def test_completion_only_histories_get_no_realtime_edges():
    """Ops without witnessed invocations never gain RT edges (advisor
    finding r3: completion times alone cannot prove realtime order),
    and process-less minimal histories don't crash the pairing."""
    hist = H([["r", "x", [2]]],
             [["append", "x", 2]])
    res = ap.analyze(hist)           # ok-only: serializable, no RT
    assert res["valid"] is True
    minimal = [{"type": "ok", "f": "txn", "index": 0,
                "value": [["append", "x", 1]]}]
    assert ap.analyze(minimal)["valid"] is True
