"""Clock-skew plot and linearizability witness rendering tests
(reference checker/clock.clj; checker.clj:206-212 linear.svg)."""

import os

import pytest

from jepsen_tpu import store
from jepsen_tpu.checker import checkers as ck
from jepsen_tpu.checker import clock as cclock
from jepsen_tpu.checker import linear_report


@pytest.fixture(autouse=True)
def store_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))


def _test_map():
    return {"name": "clocky", "start-time": "20260730T000000.000000+0000",
            "nodes": ["n1.foo.com", "n2.foo.com"]}


SEC = 1_000_000_000


def test_history_datasets_and_short_names():
    hist = [
        {"type": "info", "process": "nemesis", "f": "bump", "time": 1 * SEC,
         "clock_offsets": {"n1.foo.com": 0.5, "n2.foo.com": -0.25}},
        {"type": "info", "process": "nemesis", "f": "reset",
         "time": 3 * SEC, "clock_offsets": {"n1.foo.com": 0.0}},
        {"type": "ok", "process": 0, "f": "read", "time": 4 * SEC},
    ]
    ds = cclock.history_datasets(hist)
    assert ds["n1.foo.com"] == [(1.0, 0.5), (3.0, 0.0), (4.0, 0.0)]
    assert ds["n2.foo.com"] == [(1.0, -0.25), (4.0, -0.25)]
    assert cclock.short_node_names(["n1.foo.com", "n2.foo.com"]) == \
        ["n1", "n2"]
    assert cclock.short_node_names(["solo"]) == ["solo"]


def test_clock_plot_writes_png():
    test = _test_map()
    hist = [
        {"type": "info", "process": "nemesis", "f": "bump", "time": 1 * SEC,
         "clock_offsets": {"n1.foo.com": 2.0, "n2.foo.com": -1.0}},
        {"type": "info", "process": "nemesis", "f": "reset",
         "time": 5 * SEC,
         "clock_offsets": {"n1.foo.com": 0.0, "n2.foo.com": 0.0}},
    ]
    r = cclock.clock_plot().check(test, hist)
    assert r["valid"] is True
    assert os.path.exists(store.path(test, "clock-skew.png"))


def test_clock_plot_no_data_no_file():
    test = _test_map()
    r = cclock.clock_plot().check(test, [{"type": "ok", "process": 0,
                                          "f": "read", "time": 0}])
    assert r["valid"] is True
    assert not os.path.exists(os.path.join(store.base_dir, "clocky"))


def _invalid_register_history():
    """Write 1 completes, then a read sees 2: not linearizable."""
    ms = 1_000_000
    return [
        {"type": "invoke", "process": 0, "f": "write", "value": 1,
         "time": 0, "index": 0},
        {"type": "ok", "process": 0, "f": "write", "value": 1,
         "time": 1 * ms, "index": 1},
        {"type": "invoke", "process": 1, "f": "read", "value": None,
         "time": 2 * ms, "index": 2},
        {"type": "ok", "process": 1, "f": "read", "value": 2,
         "time": 3 * ms, "index": 3},
    ]


def test_linearizable_failure_renders_witness():
    test = _test_map()
    checker = ck.linearizable({"model": "register", "algorithm": "wgl"})
    res = checker.check(test, _invalid_register_history())
    assert res["valid"] is False
    p = store.path(test, "linear.png")
    assert os.path.exists(p)
    assert os.path.getsize(p) > 1000


def test_render_analysis_returns_none_without_witness():
    assert linear_report.render_analysis(
        _test_map(), _invalid_register_history(), {"valid": False}) is None


def test_linearizable_valid_renders_nothing():
    test = _test_map()
    hist = _invalid_register_history()
    hist[3] = dict(hist[3], value=1)
    checker = ck.linearizable({"model": "register", "algorithm": "wgl"})
    res = checker.check(test, hist)
    assert res["valid"] is True
    assert not os.path.exists(store.path(test, "linear.png"))
