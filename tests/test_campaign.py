"""Campaign subsystem tests: matrix expansion + PL012, the parallel
scheduler (ordering independence, device slots), abort -> resume,
cross-run compile reuse counters, flake detection, and the cli
test-all fixes that ride along."""

import json
import os

import pytest

from jepsen_tpu import checker as cc
from jepsen_tpu import cli
from jepsen_tpu import client as jc
from jepsen_tpu import generator as gen
from jepsen_tpu import store
from jepsen_tpu import tests as tst
from jepsen_tpu.campaign import compile_cache, journal, plan, report
from jepsen_tpu.campaign import scheduler
from jepsen_tpu.checker import checkers as cks
from jepsen_tpu.checker.core import FnChecker
from jepsen_tpu.robust import AbortLatch


@pytest.fixture(autouse=True)
def store_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))


def dummy_test(**kw):
    t = tst.noop_test()
    t["ssh"] = {"dummy?": True}
    t["obs?"] = False
    t.update(kw)
    return t


class OkClient(jc.Client):
    def open(self, test, node):
        return self

    def invoke(self, test, op):
        out = dict(op)
        out["type"] = "ok"
        return out


def quick_cell(name, valid=True, ops=3):
    checker = cc.noop() if valid else FnChecker(
        lambda t, h, o: {"valid": False}, "nope")
    return dummy_test(
        name=name, nodes=["n1"], concurrency=1, client=OkClient(),
        checker=checker,
        generator=gen.clients(gen.limit(ops, gen.repeat({"f": "read"}))))


# ---------------------------------------------------------------------------
# plan: matrix expansion + PL012


def test_matrix_expansion_deterministic():
    cells = plan.expand({"base": {"time-limit": 5},
                         "axes": {"workload": ["a", "b"],
                                  "concurrency": [2, 4]}})
    assert len(cells) == 4
    assert cells[0]["id"] == "concurrency=2,workload=a"
    assert cells[0]["params"] == {"time-limit": 5, "concurrency": 2,
                                  "workload": "a"}
    # deterministic order: sorted axis names, values in given order
    assert [c["id"] for c in cells] == [
        "concurrency=2,workload=a", "concurrency=2,workload=b",
        "concurrency=4,workload=a", "concurrency=4,workload=b"]
    # groups strip the seed axis only
    cells = plan.expand({"axes": {"workload": ["a"], "seed": [0, 1]}})
    assert {c["group"] for c in cells} == {"workload=a"}
    assert {c["id"] for c in cells} == {"seed=0,workload=a",
                                        "seed=1,workload=a"}


def test_matrix_seeds_shorthand_and_plain_form():
    cells = plan.expand({"workload": ["a"], "seeds": 3,
                         "time-limit": 9})
    assert len(cells) == 3
    assert all(c["params"]["time-limit"] == 9 for c in cells)
    assert sorted(c["params"]["seed"] for c in cells) == [0, 1, 2]


def test_pl012_empty_matrix_is_error():
    diags = plan.lint({})
    assert any(d.code == "PL012" and d.severity == "error"
               for d in diags)
    with pytest.raises(plan.CampaignPlanError):
        plan.validate({"axes": {}})
    with pytest.raises(plan.CampaignPlanError):
        plan.validate({"axes": {"workload": []}})


def test_pl012_duplicate_cell_ids_and_seed_collisions():
    # "a b" and "a_b" sanitize to the same id fragment -> duplicate ids
    diags = plan.lint({"axes": {"workload": ["a b", "a_b"]}})
    assert any(d.code == "PL012" and d.severity == "error"
               and "duplicate" in d.message for d in diags)
    diags = plan.lint({"axes": {"seed": [1, 1]}})
    assert any(d.code == "PL012" and "seed" in d.message.lower()
               for d in diags)


def test_pl012_per_cell_knobs_via_pl011_rules():
    diags = plan.lint({"base": {"op-timeout-ms": 99000},
                       "axes": {"time-limit-s": [60, 120]}})
    warn = [d for d in diags if d.code == "PL012"]
    # 99000 ms >= 60 s deadline trips in exactly the time-limit-s=60
    # cell
    assert any("op-timeout-ms" in d.message for d in warn)
    assert any("time-limit-s=60" in d.location for d in warn)
    assert not any("time-limit-s=120" in d.location for d in warn)


# ---------------------------------------------------------------------------
# scheduler: parallel execution, ordering independence, device slots


def outcome_map(rep):
    return {r["cell"]: r["outcome"] for r in rep["cells"]}


def test_campaign_outcomes_independent_of_parallelism():
    def cells():
        return [
            {"id": "ok-1", "test": quick_cell("ok-1")},
            {"id": "ok-2", "test": quick_cell("ok-2")},
            {"id": "bad-1", "test": quick_cell("bad-1", valid=False)},
            {"id": "bad-2", "test": quick_cell("bad-2", valid=False)},
        ]

    seq = scheduler.run_cells(cells(), campaign_id="seq", parallel=1)
    par = scheduler.run_cells(cells(), campaign_id="par", parallel=3)
    want = {"ok-1": True, "ok-2": True, "bad-1": False, "bad-2": False}
    assert outcome_map(seq) == want
    assert outcome_map(par) == want
    assert seq["status"] == par["status"] == "complete"
    # journal + report landed on disk, campaign dir excluded from tests
    meta = json.load(open(store.campaign_path("par", "campaign.json")))
    assert meta["status"] == "complete"
    assert sorted(meta["cells"]) == sorted(want)
    assert "campaigns" not in store.test_names()
    assert set(store.campaigns()) == {"seq", "par"}
    # exit-code plumbing: failures beat successes
    assert cli.test_all_exit_code(par["results"]) == 1


def test_device_slot_serializes_checks():
    import threading
    active = []
    peak = []
    lock = threading.Lock()

    def slow_check(t, h, o):
        with lock:
            active.append(1)
            peak.append(len(active))
        import time
        time.sleep(0.05)
        with lock:
            active.pop()
        return {"valid": True}

    cells = [{"id": f"c{i}",
              "test": quick_cell(f"c{i}")} for i in range(4)]
    for c in cells:
        c["test"]["checker"] = FnChecker(slow_check, "slow")
    rep = scheduler.run_cells(cells, campaign_id="slots", parallel=4,
                              device_slots=1)
    assert all(o is True for o in outcome_map(rep).values())
    assert max(peak) == 1, "device-slot semaphore must serialize checks"


# ---------------------------------------------------------------------------
# abort -> journal -> resume


def test_abort_mid_campaign_then_resume_skips_completed():
    latch = AbortLatch()
    ran = []

    class AbortingClient(OkClient):
        def __init__(self, after):
            self.after = after
            self.n = 0

        def invoke(self, test, op):
            self.n += 1
            if self.n == self.after:
                latch.set("SIGINT")
            return super().invoke(test, op)

    def build_cells(counter):
        cells = []
        for i in range(4):
            name = f"cell-{i}"
            client = AbortingClient(3) if i == 1 else OkClient()

            def mk(params, name=name, client=client):
                counter.append(name)
                return dummy_test(
                    name=name, nodes=["n1"], concurrency=1,
                    client=client, checker=cc.noop(),
                    generator=gen.clients(gen.limit(
                        6, gen.repeat({"f": "read"}))))

            cells.append({"id": name, "build": mk, "params": {}})
        return cells

    rep = scheduler.run_cells(build_cells(ran), campaign_id="abrt",
                              parallel=1, latch=latch)
    assert rep["status"] == "aborted"
    assert rep["abort-reason"] == "SIGINT"
    # cell-0 finished, cell-1 aborted mid-run, cells 2/3 never started
    assert ran == ["cell-0", "cell-1"]
    om = outcome_map(rep)
    assert om["cell-0"] is True
    assert om["cell-1"] == "aborted"
    assert "cell-2" not in om and "cell-3" not in om
    # the journal survived with exactly those records
    jr = journal.CampaignJournal("abrt")
    assert set(jr.completed()) == {"cell-0"}
    assert (json.load(open(jr.meta_path))["status"]) == "aborted"
    # resume: only unfinished cells execute
    ran2 = []
    rep2 = scheduler.run_cells(build_cells(ran2), campaign_id="abrt",
                               parallel=1, resume=True,
                               latch=AbortLatch())
    assert sorted(ran2) == ["cell-1", "cell-2", "cell-3"]
    om2 = outcome_map(rep2)
    assert om2 == {f"cell-{i}": True for i in range(4)}
    assert rep2["status"] == "complete"
    assert rep2["summary"]["skipped-resumed"] == 1
    assert cli.test_all_exit_code(rep2["results"]) == 0


def test_own_deadline_abort_is_terminal_not_resumed():
    """A cell that aborts on its OWN time-limit-s deadline (no campaign
    latch) ran as planned: it must journal a terminal outcome, or
    --resume would re-run it to the same deadline forever."""
    class SlowClient(OkClient):
        def invoke(self, test, op):
            import time
            time.sleep(0.05)
            return super().invoke(test, op)

    t = dummy_test(
        name="deadline", nodes=["n1"], concurrency=1,
        client=SlowClient(), checker=cc.noop(),
        **{"time-limit-s": 0.3, "abort-grace-s": 0.5},
        generator=gen.clients(gen.repeat({"f": "read"})))
    rep = scheduler.run_cells([{"id": "d", "test": t}],
                              campaign_id="dl", parallel=1)
    assert rep["status"] == "complete"
    rec = rep["cells"][0]
    assert rec["outcome"] is True          # salvaged + checked verdict
    assert rec["abort-reason"] == "time-limit"
    assert set(journal.CampaignJournal("dl").completed()) == {"d"}


def test_resume_guards():
    with pytest.raises(scheduler.CampaignError):
        scheduler.run_cells([], campaign_id="nope", resume=True)
    with pytest.raises(scheduler.CampaignError):
        scheduler.run_cells([], resume=True)  # empty store, no latest
    scheduler.run_cells([{"id": "a", "test": quick_cell("a")}],
                        campaign_id="g1")
    # resuming with a mismatched matrix is refused
    with pytest.raises(scheduler.CampaignError):
        scheduler.run_cells([{"id": "b", "test": quick_cell("b")}],
                            campaign_id="g1", resume=True)
    # ... and so is starting FRESH over an existing campaign id (the
    # journal would mix two runs' records)
    with pytest.raises(scheduler.CampaignError):
        scheduler.run_cells([{"id": "a", "test": quick_cell("a")}],
                            campaign_id="g1")
    # without an id, resume picks the latest campaign
    rep = scheduler.run_cells([{"id": "a", "test": quick_cell("a")}],
                              resume=True)
    assert rep["campaign"] == "g1"
    assert rep["summary"]["skipped-resumed"] == 1


def test_resume_refuses_stale_aborted_cells_not_in_plan():
    """A non-terminal ('aborted') record for a cell the new plan no
    longer contains must refuse the resume -- it would otherwise haunt
    every later report and exit code."""
    jr = journal.CampaignJournal("stale")
    jr.write_meta({"status": "aborted", "cells": ["old", "keep"]})
    jr.append_cell({"cell": "old", "outcome": "aborted"})
    with pytest.raises(scheduler.CampaignError):
        scheduler.run_cells([{"id": "keep", "test": quick_cell("keep")}],
                            campaign_id="stale", resume=True)


def test_journal_drops_torn_final_line():
    jr = journal.CampaignJournal("torn")
    jr.append_cell({"cell": "a", "outcome": True})
    with open(jr.cells_path, "a") as f:
        f.write('{"cell": "b", "outc')   # killed mid-append
    assert [r["cell"] for r in jr.records()] == ["a"]
    assert set(jr.completed()) == {"a"}
    # a resume appends ONTO the torn tail: the fragment must be
    # terminated, not merged into the new record (which would corrupt
    # both and crash every later read)
    jr.append_cell({"cell": "b", "outcome": True})
    jr.append_cell({"cell": "c", "outcome": True})
    assert [r["cell"] for r in jr.records()] == ["a", "b", "c"]
    assert set(jr.completed()) == {"a", "b", "c"}


def test_hard_abort_still_finalizes_journal_and_report():
    """A KeyboardInterrupt escaping a cell (second SIGINT = hard
    abort) must not skip finalize: campaign.json flips to "aborted"
    and report.json lands before the exception propagates."""
    def ki_run(test):
        if test["campaign"]["cell"] == "k-1":
            raise KeyboardInterrupt("hard abort")
        return {**test, "results": {"valid": True}}

    cells = [{"id": f"k-{i}", "test": quick_cell(f"k-{i}")}
             for i in range(3)]
    with pytest.raises(KeyboardInterrupt):
        scheduler.run_cells(cells, campaign_id="hard", parallel=1,
                            run_fn=ki_run)
    jr = journal.CampaignJournal("hard")
    assert jr.load_meta()["status"] == "aborted"
    rep = jr.load_report()
    assert rep["status"] == "aborted"
    assert [r["cell"] for r in jr.records()] == ["k-0"]
    assert cli.campaign_exit_code(rep) == 2


def test_obs_bind_overlap_keeps_live_binding():
    """The first of two overlapping per-run bindings to exit must not
    null out its still-running sibling's sinks (campaign cells overlap
    core.runs; identity-guarded restore in obs.bind)."""
    from jepsen_tpu import obs
    t1, r1 = obs.Tracer(), obs.Registry()
    t2, r2 = obs.Tracer(), obs.Registry()
    cm1 = obs.bind(t1, r1)
    cm1.__enter__()
    cm2 = obs.bind(t2, r2)
    cm2.__enter__()
    cm1.__exit__(None, None, None)       # first cell finishes first
    try:
        assert obs.registry() is r2      # sibling's binding survives
        obs.inc("x")
        assert r2.counter_value("x") == 1
    finally:
        cm2.__exit__(None, None, None)
    # last scope out unbinds cleanly: no stale pair leaks
    assert obs.registry() is None and obs.tracer() is None


# ---------------------------------------------------------------------------
# cross-run compile reuse


def register_history_client():
    class RegClient(jc.Client):
        def __init__(self):
            self.value = None

        def open(self, test, node):
            return self

        def invoke(self, test, op):
            out = dict(op)
            if op["f"] == "write":
                self.value = op["value"]
            else:
                out["value"] = self.value
            out["type"] = "ok"
            return out

    return RegClient()


def lin_cell(name):
    ops = []
    for i in range(4):
        ops.append({"type": "invoke", "f": "write", "value": i})
        ops.append({"type": "invoke", "f": "read", "value": None})
    it = iter(ops)

    def next_op(test, ctx):
        return next(it, None)

    t = dummy_test(
        name=name, nodes=["n1"], concurrency=1,
        client=register_history_client(),
        checker=cks.linearizable({"model": "register",
                                  "algorithm": "jax-wgl"}),
        generator=gen.clients(next_op))
    t["obs?"] = True     # the per-cell metrics.json is the assertion
    return t


def test_compile_cache_hits_across_shape_identical_cells():
    compile_cache.reset()
    cells = [{"id": "lin-1", "test": lin_cell("lin-1")},
             {"id": "lin-2", "test": lin_cell("lin-2")}]
    rep = scheduler.run_cells(cells, campaign_id="cc", parallel=1)
    assert outcome_map(rep) == {"lin-1": True, "lin-2": True}
    # identical deterministic histories -> identical plan shapes -> the
    # second cell's search is a ledger hit (jit cache reuse)
    assert rep["compile_cache"]["hits"] >= 1
    assert rep["compile_cache"]["misses"] >= 1
    # campaign-level metrics carry the same numbers
    metrics = json.load(open(store.campaign_path("cc", "metrics.json")))
    assert metrics["gauges"]["campaign.compile_cache.hits"] >= 1
    # and the obs mirror put per-cell counters in the second cell's own
    # run metrics
    run_metrics = json.load(open(os.path.join(
        store.base_dir, "lin-2", "latest", "metrics.json")))
    hits = [v for k, v in run_metrics["counters"].items()
            if k.startswith("campaign.compile_cache.hits")]
    assert sum(hits) >= 1


def test_compile_cache_ledger_and_floor():
    compile_cache.reset()
    key = ("spec", 64, 2, 4)
    assert compile_cache.note("e", key) is False
    assert compile_cache.note("e", key) is True
    assert compile_cache.note("e", ("spec", 128, 2, 4)) is False
    s = compile_cache.stats()
    assert s["hits"] == 1 and s["misses"] == 2 and s["shapes"] == 2
    assert compile_cache.delta({"hits": 1, "misses": 0}) == \
        {"hits": 0, "misses": 2}
    assert compile_cache.bucket(900, 64) == 1024
    with compile_cache.bucket_floor(2048):
        assert compile_cache.n_floor() == 2048
        from jepsen_tpu.checker import jax_wgl
        assert jax_wgl._n_floor() == 2048
        assert jax_wgl._bucket(900, jax_wgl._n_floor()) == 2048
    assert compile_cache.n_floor() == compile_cache.DEFAULT_N_FLOOR
    compile_cache.reset()


# ---------------------------------------------------------------------------
# report: flakes + triage


def test_flake_detection_on_divergent_seeded_validity():
    records = [
        {"cell": "seed=0,w=a", "group": "w=a", "outcome": True,
         "valid": True},
        {"cell": "seed=1,w=a", "group": "w=a", "outcome": False,
         "valid": False},
        {"cell": "seed=0,w=b", "group": "w=b", "outcome": True,
         "valid": True},
        {"cell": "seed=1,w=b", "group": "w=b", "outcome": True,
         "valid": True},
        # aborted cells carry no verdict: never flake evidence
        {"cell": "seed=2,w=b", "group": "w=b", "outcome": "aborted",
         "valid": "unknown"},
    ]
    rep = report.summarize(records)
    assert [f["group"] for f in rep["flakes"]] == ["w=a"]
    assert rep["flakes"][0]["validities"] == ["False", "True"]
    text = report.render_text(rep)
    assert "w=a" in text and "flaky" in text


def test_triage_groups_by_failure_signature():
    records = [
        {"cell": "c1", "outcome": "crashed",
         "error": "Traceback ...\nRuntimeError: boom"},
        {"cell": "c2", "outcome": "crashed",
         "error": "Traceback ...\nRuntimeError: boom"},
        {"cell": "c3", "outcome": "aborted", "abort-reason": "SIGINT"},
        {"cell": "c4", "outcome": True},
    ]
    tri = report.summarize(records)["triage"]
    assert tri["crashed: RuntimeError: boom"] == ["c1", "c2"]
    assert tri["aborted: SIGINT"] == ["c3"]
    assert not any("c4" in v for v in tri.values())


# ---------------------------------------------------------------------------
# cli satellites: crash containment, cell ids, exit codes


def test_test_all_records_prepare_crash_and_continues():
    # a malformed plan (nodes not a list) crashes prepare_test; the
    # suite must record it as crashed and still run the next test
    bad = {"name": "bad", "nodes": 42}
    good = quick_cell("good")
    results = cli.test_all_run_tests([bad, good])
    assert len(results["crashed"]) == 1
    assert results[True] and "good" in str(results[True][0])
    assert cli.test_all_exit_code(results) == 255


def test_test_all_summary_includes_cell_ids(capsys):
    t = quick_cell("celltest")
    t["campaign"] = {"id": "x", "cell": "seed=1,workload=w"}
    results = cli.test_all_run_tests([t])
    entry = results[True][0]
    assert entry["cell"] == "seed=1,workload=w"
    cli.test_all_print_summary(results)
    out = capsys.readouterr().out
    assert "[seed=1,workload=w]" in out
    assert "celltest" in out


def test_campaign_exit_code_covers_unrecorded_aborts():
    # SIGINT between cells: every recorded cell passed, but the
    # campaign is aborted with unrun cells -> must NOT exit 0
    rep = {"status": "aborted", "results": {True: [{"cell": "a"}]}}
    assert cli.campaign_exit_code(rep) == 2
    rep = {"status": "aborted",
           "results": {True: [{"cell": "a"}], False: [{"cell": "b"}]}}
    assert cli.campaign_exit_code(rep) == 2
    rep = {"status": "aborted", "results": {"crashed": [{"cell": "a"}]}}
    assert cli.campaign_exit_code(rep) == 255
    rep = {"status": "complete", "results": {True: [{"cell": "a"}]}}
    assert cli.campaign_exit_code(rep) == 0


def test_scheduler_contains_non_dict_build_crash():
    cells = [{"id": "bogus", "build": lambda params: "not a test",
              "params": {}},
             {"id": "fine", "test": quick_cell("fine")}]
    rep = scheduler.run_cells(cells, campaign_id="bog", parallel=1)
    om = outcome_map(rep)
    assert om["bogus"] == "crashed"
    assert om["fine"] is True
    assert rep["status"] == "complete"


def test_exit_code_order_with_aborted():
    # reference order 255 > 2 > 1 > 0; aborted ranks with unknown
    assert cli.test_all_exit_code({"aborted": ["x"]}) == 2
    assert cli.test_all_exit_code({"aborted": ["x"], False: ["y"]}) == 2
    assert cli.test_all_exit_code({"crashed": ["x"],
                                   "aborted": ["y"]}) == 255
    assert cli.test_all_exit_code({True: ["x"]}) == 0


def test_test_all_parallel_routes_through_campaign(capsys):
    cmd = cli.test_all_cmd({
        "tests-fn": lambda o: [quick_cell("ta-1"), quick_cell("ta-2")]})
    with pytest.raises(SystemExit) as ei:
        cmd["test-all"]["run"]({"parallel": 2, "device-slots": 1,
                                "campaign-id": "ta", "resume": False})
    assert ei.value.code == 0
    recs = store.load_campaign_records("ta")
    assert {r["cell"] for r in recs} == {"ta-1", "ta-2"}
    out = capsys.readouterr().out
    assert "[ta-1]" in out and "[ta-2]" in out
    # and --resume alone reruns nothing
    with pytest.raises(SystemExit) as ei:
        cmd["test-all"]["run"]({"parallel": 1, "device-slots": 1,
                                "campaign-id": None, "resume": True})
    assert ei.value.code == 0
    assert len(store.load_campaign_records("ta")) == 2
    # --campaign-id ALONE routes through the scheduler too (it would
    # otherwise be silently ignored and leave nothing to resume)
    with pytest.raises(SystemExit) as ei:
        cmd["test-all"]["run"]({"parallel": 1, "device-slots": 1,
                                "campaign-id": "ta2", "resume": False})
    assert ei.value.code == 0
    assert len(store.load_campaign_records("ta2")) == 2


def test_parse_axes():
    axes = cli.parse_axes(["workload=a,b", "concurrency=2,4"], seeds=2)
    assert axes == {"workload": ["a", "b"], "concurrency": [2, 4],
                    "seed": [0, 1]}
    with pytest.raises(cli.CliError):
        cli.parse_axes(["oops"])


# ---------------------------------------------------------------------------
# web: campaign index


def test_web_campaigns_page():
    rep_cells = [{"id": "w-ok", "test": quick_cell("w-ok")},
                 {"id": "w-bad", "test": quick_cell("w-bad",
                                                    valid=False)}]
    scheduler.run_cells(rep_cells, campaign_id="webc", parallel=1)
    from jepsen_tpu import web
    page = web._campaigns_page()
    assert "webc" in page
    assert "w-ok" in page and "w-bad" in page
    assert "valid-false" in page
    # cell rows link into the per-run store directories
    assert "/files/w-ok/" in page
    # resumed campaigns render latest-record-per-cell, not raw journal
    jr = journal.CampaignJournal("webc")
    jr.append_cell({"cell": "w-ok", "outcome": "aborted",
                    "valid": "unknown", "path": None})
    jr.append_cell({"cell": "w-ok", "outcome": True, "valid": True,
                    "path": None})
    page = web._campaigns_page()
    assert page.count("<td>w-ok</td>") == 1
    assert "2/2 cells" in page


def test_cli_campaign_end_to_end():
    from jepsen_tpu import __main__ as main_mod
    # the acceptance-criteria shape: a 2x2 CPU campaign, --parallel 2
    with pytest.raises(SystemExit) as ei:
        main_mod.main(["campaign", "--no-ssh", "--time-limit", "1",
                       "--axis", "workload=noop,bank", "--seeds", "2",
                       "--parallel", "2", "--campaign-id", "smoke"])
    assert ei.value.code == 0
    meta = json.load(open(store.campaign_path("smoke",
                                              "campaign.json")))
    assert meta["id"] == "smoke"
    assert meta["status"] == "complete"
    assert len(meta["cells"]) == 4
    recs = store.load_campaign_records("smoke")
    assert len(recs) == 4
    assert all(r["outcome"] is True for r in recs)
    report_ = json.load(open(store.campaign_path("smoke",
                                                 "report.json")))
    assert report_["summary"]["outcomes"] == {"True": 4}
    # rerunning with --resume is a no-op: everything already journaled
    with pytest.raises(SystemExit) as ei:
        main_mod.main(["campaign", "--no-ssh", "--time-limit", "1",
                       "--axis", "workload=noop,bank", "--seeds", "2",
                       "--campaign-id", "smoke", "--resume"])
    assert ei.value.code == 0
    assert len(store.load_campaign_records("smoke")) == 4


def test_cli_campaign_lint_dry_run(capsys):
    from jepsen_tpu import __main__ as main_mod
    with pytest.raises(SystemExit) as ei:
        main_mod.main(["campaign", "--no-ssh", "--seeds", "2",
                       "--lint"])
    assert ei.value.code == 0
    out = capsys.readouterr().out
    assert "seed=0" in out and "seed=1" in out
    # an empty matrix is a PL012 error: lint exits 1, nothing runs
    with pytest.raises(SystemExit) as ei:
        main_mod.main(["campaign", "--no-ssh", "--lint"])
    assert ei.value.code == 1
    assert store.campaigns() == []


def test_store_logging_stack_survives_overlap():
    """Overlapping per-test log scopes (parallel cells): the first run
    to finish detaches only its OWN jepsen.log handler; the sibling's
    file keeps receiving records."""
    import logging
    ts = "20260803T000000.000000+0000"
    ta = {"name": "log-a", "start-time": ts}
    tb = {"name": "log-b", "start-time": ts}
    ha = store.start_logging(ta)
    hb = store.start_logging(tb)
    log = logging.getLogger("campaign-log-test")
    log.info("while-both")
    store.stop_logging(ha)           # A finishes first
    log.info("after-a-stopped")
    store.stop_logging(hb)
    store.stop_logging(hb)           # idempotent
    with open(store.path(tb, "jepsen.log")) as f:
        b_log = f.read()
    assert "while-both" in b_log
    assert "after-a-stopped" in b_log     # B was NOT severed
    with open(store.path(ta, "jepsen.log")) as f:
        a_log = f.read()
    assert "after-a-stopped" not in a_log


def test_axis_concurrency_suffix_syntax():
    """A concurrency axis may use the documented '3n' form: the value
    lands after test_opt_fn ran, so the build re-parses it."""
    seen = []

    def tf(o):
        seen.append(o["concurrency"])
        return quick_cell(f"c{o['concurrency']}")

    cmd = cli.campaign_cmd({"test-fn": tf})
    with pytest.raises(SystemExit) as ei:
        cmd["campaign"]["run"]({"axis": ["concurrency=2n,3n"],
                                "seeds": None, "parallel": 1,
                                "device-slots": 1,
                                "campaign-id": "cnx", "resume": False,
                                "nodes": ["n1", "n2"]})
    assert ei.value.code == 0
    assert sorted(seen) == [4, 6]


def test_unique_start_times_for_same_name_cells():
    s1 = scheduler._unique_start_time("dup")
    s2 = scheduler._unique_start_time("dup")
    assert s1 != s2


def test_core_run_marks_campaign_serializable():
    t = quick_cell("serial")
    rep = scheduler.run_cells([{"id": "c", "test": t}],
                              campaign_id="ser", parallel=1)
    path = rep["cells"][0]["path"]
    saved = json.load(open(os.path.join(path, "test.json")))
    assert saved["campaign"]["id"] == "ser"
    assert saved["campaign"]["cell"] == "c"
