"""Interpreter tests with fake in-process clients (reference test level 2:
test/jepsen/generator/interpreter_test.clj)."""

import time

from jepsen_tpu import client as jc
from jepsen_tpu import generator as gen
from jepsen_tpu import interpreter, nemesis


class OkClient(jc.Client):
    """Sleeps 5 ms and returns ok (interpreter_test.clj:18-34)."""

    def invoke(self, test, op):
        time.sleep(0.005)
        out = dict(op)
        out["type"] = "ok"
        return out


class CrashClient(jc.Client):
    def __init__(self, counter):
        self.counter = counter

    def open(self, test, node):
        self.counter["opens"] += 1
        return self

    def close(self, test):
        self.counter["closes"] += 1

    def invoke(self, test, op):
        raise RuntimeError("boom")


def _base_test(**kw):
    t = {"concurrency": 4, "nodes": ["n1", "n2"],
         "client": OkClient(), "nemesis": nemesis.noop,
         "generator": None}
    t.update(kw)
    return t


def test_simple_run():
    test = _base_test(
        generator=gen.clients(gen.limit(20, gen.repeat({"f": "read"}))))
    h = interpreter.run(test)
    invokes = [o for o in h if o["type"] == "invoke"]
    oks = [o for o in h if o["type"] == "ok"]
    assert len(invokes) == 20
    assert len(oks) == 20
    # times are monotone nondecreasing
    times = [o["time"] for o in h]
    assert times == sorted(times)
    # each completion pairs with its invocation by process
    open_ = {}
    for o in h:
        if o["type"] == "invoke":
            assert o["process"] not in open_
            open_[o["process"]] = o
        else:
            inv = open_.pop(o["process"])
            assert inv["f"] == o["f"]


def test_crash_reassigns_process():
    counter = {"opens": 0, "closes": 0}
    test = _base_test(
        client=CrashClient(counter),
        generator=gen.clients(gen.limit(8, gen.repeat({"f": "read"}))))
    h = interpreter.run(test)
    infos = [o for o in h if o["type"] == "info"]
    assert len(infos) == 8
    procs = {o["process"] for o in h if o["type"] == "invoke"}
    assert len(procs) == 8  # every crash burns a process id
    # crashed clients are closed and fresh ones opened per process
    assert counter["opens"] == 8
    assert counter["closes"] >= 7


def test_nemesis_routing():
    class RecordingNemesis(nemesis.Nemesis):
        def __init__(self):
            self.ops = []

        def invoke(self, test, op):
            self.ops.append(op)
            out = dict(op)
            out["type"] = "info"
            return out

    nem = RecordingNemesis()
    test = _base_test(
        nemesis=nem,
        generator=gen.any(
            gen.clients(gen.limit(4, gen.repeat({"f": "read"}))),
            gen.nemesis(gen.limit(2, gen.repeat({"f": "break"})))))
    h = interpreter.run(test)
    assert len(nem.ops) == 2
    assert all(o["process"] == "nemesis" for o in nem.ops)
    nem_ops = [o for o in h if o["process"] == "nemesis"]
    assert len(nem_ops) == 4  # 2 invokes + 2 infos


def test_time_limited_run():
    test = _base_test(
        generator=gen.clients(
            gen.time_limit(0.3, gen.repeat({"f": "read"}))))
    t0 = time.monotonic()
    h = interpreter.run(test)
    dt = time.monotonic() - t0
    assert dt < 5
    assert len(h) > 0


def test_sleep_and_log_excluded_from_history():
    test = _base_test(
        generator=gen.clients([gen.log("hi"), gen.sleep(0.01),
                               {"f": "read"}]))
    h = interpreter.run(test)
    assert all(o["type"] not in ("sleep", "log") for o in h)
    assert any(o.get("f") == "read" for o in h)


def test_generator_exception_propagates():
    def bad(test, ctx):
        raise ValueError("bad generator")

    test = _base_test(generator=gen.clients(bad))
    try:
        interpreter.run(test)
        raise AssertionError("expected exception")
    except RuntimeError as e:
        assert "bad generator" in str(e.__cause__ or e) or \
            "Generator threw" in str(e)
