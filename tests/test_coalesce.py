"""Cross-tenant batch coalescing tests (fleet.service.Coalescer):
verdict equivalence coalesced-vs-solo with valid AND invalid
submissions mixed in one batch, per-request deadline isolation (a slow
tenant's timeout can't flip or delay a batchmate's verdict),
batcher-crash fallback containment, cross-tenant compile-ledger hits
on shape-identical submissions, the /api/metrics coalesce family,
planlint PL020, and the web.serve queue-wait-s=0 regression."""

import threading
import time

import pytest

from jepsen_tpu import store, web
from jepsen_tpu.analysis import planlint
from jepsen_tpu.campaign import compile_cache
from jepsen_tpu.fleet import service
from jepsen_tpu.parallel import keyshard


@pytest.fixture(autouse=True)
def service_state(tmp_path, monkeypatch):
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))
    compile_cache.reset()
    service.reset()
    yield
    service.reset()
    compile_cache.reset()


def burst_hist(bursts=2, stale_read=False):
    """Concurrent write||write bursts + a final read: ambiguous enough
    that no fast path decides it, so the submission really reaches the
    device batch. ``stale_read`` reads a value that WAS written (so
    invalidity needs the real search too, not the state
    abstraction)."""
    ev = []

    def e(t, p, f, v):
        ev.append({"type": t, "process": p, "f": f, "value": v})

    for j in range(bursts):
        x = j * 10
        e("invoke", 0, "write", x)
        e("invoke", 1, "write", x + 1)
        e("ok", 0, "write", x)
        e("ok", 1, "write", x + 1)
        e("invoke", 0, "write", x + 5)
        e("ok", 0, "write", x + 5)
    e("invoke", 2, "read", None)
    e("ok", 2, "read", 0 if stale_read else (bursts - 1) * 10 + 5)
    return ev


def concurrent_checks(payloads, callers):
    """Fire the payloads concurrently (one thread each) so they land
    inside one coalescing window; returns results in order."""
    results = [None] * len(payloads)
    errors = []

    def call(i):
        try:
            results[i] = service.check_history(payloads[i],
                                               caller=callers[i])
        except Exception as exc:  # noqa: BLE001 - surfaced below
            errors.append((i, exc))

    threads = [threading.Thread(target=call, args=(i,))
               for i in range(len(payloads))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not errors, errors
    assert all(r is not None for r in results)
    return results


# ---------------------------------------------------------------------------
# verdict equivalence: coalesced vs solo, mixed valid+invalid batches

def test_coalesced_verdicts_match_solo_mixed_batch():
    """THE equivalence gate: four tenants (two valid, two with a stale
    read) submitted concurrently through the batcher must get exactly
    the verdicts the solo path gives, and at least one batch must
    really have merged strangers (owners >= 2)."""
    payloads = [
        {"history": burst_hist(2), "model": "cas-register"},
        {"history": burst_hist(2, stale_read=True),
         "model": "cas-register"},
        {"history": burst_hist(3), "model": "cas-register"},
        {"history": burst_hist(3, stale_read=True),
         "model": "cas-register"},
    ]
    solo = [service.check_history({**p, "coalesce": False},
                                  caller=f"solo-{i}")
            for i, p in enumerate(payloads)]
    service.configure_coalesce(enabled=True, window_ms=200)
    coal = concurrent_checks(payloads,
                             [f"tenant-{i}" for i in range(4)])
    assert [r["valid"] for r in coal] == [r["valid"] for r in solo] \
        == [True, False, True, False]
    st = service.coalescer().stats()
    assert st["batches"] >= 1 and st["segments"] >= 2
    assert max(r.get("coalesced", {}).get("owners", 0)
               for r in coal) >= 2


def test_coalesced_keyed_and_register_model_match_solo():
    """Keyed histories split per key; each key's segments ride the
    same batcher. A different model (register) groups separately and
    still answers correctly."""
    keyed = []
    for k, bad in (("a", False), ("b", True)):
        for op in burst_hist(2, stale_read=bad):
            op = dict(op)
            op["value"] = [k, op["value"]]
            keyed.append(op)
    service.configure_coalesce(enabled=True, window_ms=100)
    r = service.check_history({"history": keyed, "model": "register",
                               "keyed": True}, caller="kt")
    assert r["valid"] is False
    assert r["keys"]["a"]["valid"] is True
    assert r["keys"]["b"]["valid"] is False


def test_cpu_engines_bypass_coalescer():
    """Only jax-wgl submissions batch: the CPU engines take the solo
    path untouched (PL020 calls coalescing with them a no-op)."""
    service.configure_coalesce(enabled=True, window_ms=50)
    for engine in ("wgl", "linear"):
        r = service.check_history(
            {"history": burst_hist(2, stale_read=True),
             "model": "cas-register", "engine": engine},
            caller=f"cpu-{engine}")
        assert r["valid"] is False, engine
    assert service.coalescer().stats()["batches"] == 0


def test_payload_coalesce_opt_out_and_validation():
    service.configure_coalesce(enabled=True, window_ms=50)
    r = service.check_history({"history": burst_hist(2),
                               "model": "cas-register",
                               "coalesce": False}, caller="opt-out")
    assert r["valid"] is True
    assert service.coalescer().stats()["batches"] == 0
    with pytest.raises(service.ApiError) as e:
        service.check_history({"history": burst_hist(2),
                               "coalesce": "yes"})
    assert e.value.status == 400


# ---------------------------------------------------------------------------
# deadline isolation + containment

def test_deadline_isolation_slow_tenant_cannot_poison_batchmate(
        monkeypatch):
    """A short-deadline tenant batched with a slow device call times
    out ALONE ("unknown" at its own deadline); its batchmate's
    definite verdict is neither flipped nor lost."""
    real = keyshard.check_batch_encoded

    def slow(spec, pairs, **kw):
        time.sleep(0.6)
        return real(spec, pairs, **kw)

    monkeypatch.setattr(keyshard, "check_batch_encoded", slow)
    service.configure_coalesce(enabled=True, window_ms=100)
    out = concurrent_checks(
        [{"history": burst_hist(2), "model": "cas-register",
          "timeout-s": 0.2},
         {"history": burst_hist(2, stale_read=True),
          "model": "cas-register", "timeout-s": 60}],
        ["hurried", "patient"])
    assert out[0]["valid"] == "unknown"
    assert "timeout" in out[0]["error"]
    assert out[1]["valid"] is False


def test_expired_segment_never_touches_the_device(monkeypatch):
    """A segment whose deadline passed while queued is answered
    "unknown" at dispatch without burning device work (and without
    shrinking batchmates' verdicts)."""
    calls = []
    real = keyshard.check_batch_encoded

    def spy(spec, pairs, **kw):
        calls.append(len(pairs))
        return real(spec, pairs, **kw)

    monkeypatch.setattr(keyshard, "check_batch_encoded", spy)
    # window far beyond the hurried tenant's deadline: it EXPIRES in
    # the queue while the patient one keeps the batch alive
    service.configure_coalesce(enabled=True, window_ms=400)
    out = concurrent_checks(
        [{"history": burst_hist(2), "model": "cas-register",
          "searchplan": False, "timeout-s": 0.05},
         {"history": burst_hist(2), "model": "cas-register",
          "searchplan": False, "timeout-s": 60}],
        ["hurried", "patient"])
    assert out[0]["valid"] == "unknown"
    assert out[1]["valid"] is True
    assert calls == [1]     # only the patient tenant's segment ran
    assert service.coalescer().stats()["expired"] == 1


def test_batcher_crash_falls_back_to_solo_path(monkeypatch):
    """Containment: a batcher that crashes outright costs the batching
    win, never the verdict -- every member re-runs solo."""
    def boom(spec, pairs, **kw):
        raise RuntimeError("injected batcher fault")

    monkeypatch.setattr(keyshard, "check_batch_encoded", boom)
    service.configure_coalesce(enabled=True, window_ms=100)
    out = concurrent_checks(
        [{"history": burst_hist(2), "model": "cas-register"},
         {"history": burst_hist(2, stale_read=True),
          "model": "cas-register"}],
        ["a", "b"])
    assert [r["valid"] for r in out] == [True, False]
    st = service.coalescer().stats()
    assert st["fallbacks"] >= 2 and st["batches"] == 0
    flat = service.slo_registry().snapshot()["counters"]
    assert flat.get("service.coalesce.fallbacks", 0) >= 2


def test_replacing_coalescer_releases_queued_segments():
    """configure_coalesce over a live coalescer stops the old one; its
    queued segments fall back solo instead of wedging the request."""
    service.configure_coalesce(enabled=True, window_ms=30_000)
    out = {}

    def call():
        out["r"] = service.check_history(
            {"history": burst_hist(2), "model": "cas-register",
             "searchplan": False}, caller="queued")

    t = threading.Thread(target=call)
    t.start()
    time.sleep(0.3)             # let the segment enqueue
    service.configure_coalesce(enabled=False)
    t.join(timeout=120)
    assert not t.is_alive()
    assert out["r"]["valid"] is True


# ---------------------------------------------------------------------------
# batching mechanics + cross-tenant compile reuse

def test_size_cap_closes_batch_before_window():
    service.configure_coalesce(enabled=True, window_ms=30_000,
                               max_segments=2)
    t0 = time.monotonic()
    out = concurrent_checks(
        [{"history": burst_hist(2), "model": "cas-register",
          "searchplan": False},
         {"history": burst_hist(2), "model": "cas-register",
          "searchplan": False}],
        ["a", "b"])
    assert [r["valid"] for r in out] == [True, True]
    assert time.monotonic() - t0 < 30          # not the 30 s window
    st = service.coalescer().stats()
    assert st["batches"] == 1 and st["segments"] == 2
    assert st["occupancy"] == 1.0


def test_cross_tenant_ledger_hits_on_shape_identical_submissions():
    """Two strangers' shape-identical submissions share one compiled
    batch search: the first coalesced batch is the miss, the second
    round's identical batch is a ledger HIT (the jit cache served the
    compile across tenants)."""
    service.configure_coalesce(enabled=True, window_ms=200)
    payloads = [{"history": burst_hist(2), "model": "cas-register",
                 "searchplan": False},
                {"history": burst_hist(2, stale_read=True),
                 "model": "cas-register", "searchplan": False}]
    first = concurrent_checks(payloads, ["tenant-a", "tenant-b"])
    assert [r["valid"] for r in first] == [True, False]
    assert all(r["coalesced"]["owners"] == 2 for r in first)
    before = compile_cache.stats()
    second = concurrent_checks(payloads, ["tenant-c", "tenant-d"])
    assert [r["valid"] for r in second] == [True, False]
    after = compile_cache.stats()
    assert after["hits"] > before["hits"]
    assert after["misses"] == before["misses"]


def test_coalesce_metrics_on_api_metrics():
    """The shed-vs-coalesce crossover pair: service.coalesce.* renders
    on /api/metrics next to admission.shed_total."""
    service.configure_coalesce(enabled=True, window_ms=100)
    concurrent_checks(
        [{"history": burst_hist(2), "model": "cas-register",
          "searchplan": False}] * 2,
        ["m-a", "m-b"])
    text = service.metrics_text()
    assert "jepsen_service_coalesce_batches" in text
    assert "jepsen_service_coalesce_segments" in text
    assert "jepsen_service_coalesce_occupancy" in text
    assert "jepsen_admission_shed_total" in text


# ---------------------------------------------------------------------------
# serve wiring + the queue-wait-s regression

def test_serve_queue_wait_zero_is_not_coerced_to_default():
    """Regression: ``opts.get("queue-wait-s") or 15.0`` coerced a
    legal explicit 0 (shed immediately, never queue) back to 15.0."""
    server = web.serve({"ip": "127.0.0.1", "port": 0,
                        "queue-wait-s": 0,
                        "budgets": {"concurrent-checks": 1}})
    try:
        assert service.admission().queue_wait_s == 0.0
    finally:
        server.shutdown()


def test_serve_enables_coalescing_by_default_and_honors_opt_out():
    server = web.serve({"ip": "127.0.0.1", "port": 0})
    try:
        assert service.coalescer() is not None
    finally:
        server.shutdown()
    server = web.serve({"ip": "127.0.0.1", "port": 0,
                        "coalesce?": False,
                        "coalesce-window-ms": 5})
    try:
        assert service.coalescer() is None
    finally:
        server.shutdown()
    server = web.serve({"ip": "127.0.0.1", "port": 0,
                        "coalesce-window-ms": 7,
                        "coalesce-max-segments": 3})
    try:
        coal = service.coalescer()
        assert coal.window_s == pytest.approx(0.007)
        assert coal.max_segments == 3
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# planlint PL020

def test_pl020_bad_knobs_are_errors():
    for cfg in ({"coalesce-window-ms": 0},
                {"coalesce-window-ms": -5},
                {"coalesce-window-ms": "fast"},
                {"coalesce-max-segments": 0},
                {"coalesce-max-segments": 2.5},
                {"coalesce-max-segments": True}):
        diags = planlint.lint_coalesce(cfg)
        assert [d.code for d in diags] == ["PL020"], cfg
        assert diags[0].severity == planlint.ERROR, cfg


def test_pl020_noop_configurations_are_warnings():
    diags = planlint.lint_coalesce({"coalesce?": True,
                                    "device-slots": 0})
    assert [d.code for d in diags] == ["PL020"]
    assert diags[0].severity == planlint.WARNING
    diags = planlint.lint_coalesce({"coalesce?": True,
                                    "engine": "linear"})
    assert [d.code for d in diags] == ["PL020"]
    assert diags[0].severity == planlint.WARNING
    # not enabled -> the no-op rules don't fire; jax-wgl is fine
    assert planlint.lint_coalesce({"device-slots": 0}) == []
    assert planlint.lint_coalesce({"coalesce?": True,
                                   "engine": "jax-wgl",
                                   "coalesce-window-ms": 25,
                                   "coalesce-max-segments": 32,
                                   "device-slots": 1}) == []


def test_pl020_rides_run_fleet_preflight():
    """A bad coalesce window refuses the fleet run exactly like the
    other preflight errors (PL014-PL019)."""
    from jepsen_tpu import fleet
    with pytest.raises(fleet.FleetError) as e:
        fleet.run_fleet([{"id": "c1", "group": {}, "params": {}}],
                        ["local"], coalesce=True,
                        coalesce_window_ms=0)
    assert "PL020" in str(e.value) \
        or "coalesce-window-ms" in str(e.value)


def test_coalescer_rejects_bad_construction():
    with pytest.raises(ValueError):
        service.Coalescer(window_s=0)
    with pytest.raises(ValueError):
        service.Coalescer(max_segments=0)
