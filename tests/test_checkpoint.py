import random, os
from jepsen_tpu.checker import jax_wgl
from jepsen_tpu.models import cas_register_spec
from jepsen_tpu.simulate import corrupt, random_history


def test_checkpoint_resume(tmp_path):
    rng = random.Random(45100)
    hist = random_history(rng, "cas-register", 6, 120, 0.05)
    e, st = cas_register_spec.encode(hist)
    ck = str(tmp_path / "frontier.npz")
    # fresh full run for the expected verdict
    want = jax_wgl.check_encoded(cas_register_spec, e, st)
    # interrupted run: tiny chunks + instant timeout -> snapshot written
    r1 = jax_wgl.check_encoded(cas_register_spec, e, st, chunk_iters=1,
                               timeout_s=0, checkpoint=ck)
    assert r1["valid"] == "unknown" and r1["error"] == "timeout"
    assert os.path.exists(ck)
    # resumed run completes from the snapshot and agrees, then cleans up
    r2 = jax_wgl.check_encoded(cas_register_spec, e, st, chunk_iters=1,
                               checkpoint=ck)
    assert r2["valid"] == want["valid"]
    assert r2["iterations"] >= r1["iterations"]
    assert not os.path.exists(ck)


def test_checkpoint_fingerprint_mismatch_ignored(tmp_path):
    rng = random.Random(45100)
    h1 = random_history(rng, "cas-register", 4, 40, 0.0)
    h2 = random_history(rng, "cas-register", 4, 40, 0.0)
    e1, st1 = cas_register_spec.encode(h1)
    e2, st2 = cas_register_spec.encode(h2)
    ck = str(tmp_path / "frontier.npz")
    r = jax_wgl.check_encoded(cas_register_spec, e1, st1, chunk_iters=1,
                              timeout_s=0, checkpoint=ck)
    assert os.path.exists(ck)
    # a different history must not resume from this snapshot
    r2 = jax_wgl.check_encoded(cas_register_spec, e2, st2, checkpoint=ck)
    assert r2["valid"] in (True, False)


def test_checkpoint_kept_on_budget_exhaustion(tmp_path):
    """An undecided max-configs run keeps its snapshot so a bigger-budget
    rerun resumes instead of restarting."""
    rng = random.Random(2)
    # corrupt: the rollout cannot decide an invalid history in one
    # iteration, so the tiny budget genuinely exhausts
    hist = corrupt(rng, random_history(rng, "cas-register", 6, 120, 0.05))
    e, st = cas_register_spec.encode(hist)
    ck = str(tmp_path / "frontier.npz")
    r1 = jax_wgl.check_encoded(cas_register_spec, e, st, chunk_iters=1,
                               max_configs=1, checkpoint=ck)
    assert r1["valid"] == "unknown"
    assert os.path.exists(ck)
    assert r1.get("checkpoint") == ck
    r2 = jax_wgl.check_encoded(cas_register_spec, e, st, checkpoint=ck)
    assert r2["valid"] in (True, False)
    assert not os.path.exists(ck)


def test_checkpoint_of_other_check_preserved(tmp_path):
    """A run pointed at another check's snapshot must not destroy it."""
    rng = random.Random(4)
    # corrupt: an undecided-after-one-iteration run is what leaves a
    # snapshot behind (valid histories now decide via the rollout).
    # Clamp the corrupted read back into the written 0-3 range so the
    # state-abstraction pre-check can't decide it without searching.
    h1 = corrupt(rng, random_history(rng, "cas-register", 6, 120, 0.05))
    for o in h1:
        if o["type"] == "ok" and o["f"] == "read" \
                and o.get("value") is not None:
            o["value"] = o["value"] % 4
    h2 = random_history(rng, "cas-register", 4, 40, 0.0)
    e1, st1 = cas_register_spec.encode(h1)
    e2, st2 = cas_register_spec.encode(h2)
    ck = str(tmp_path / "frontier.npz")
    jax_wgl.check_encoded(cas_register_spec, e1, st1, chunk_iters=1,
                          timeout_s=0, checkpoint=ck)
    before = open(ck, "rb").read()
    # a different decided check at the same path: snapshot untouched
    r = jax_wgl.check_encoded(cas_register_spec, e2, st2, checkpoint=ck)
    assert r["valid"] in (True, False)
    assert open(ck, "rb").read() == before
    # resuming the original still works
    r1 = jax_wgl.check_encoded(cas_register_spec, e1, st1, checkpoint=ck)
    assert r1["valid"] in (True, False)


def test_checkpoint_fingerprint_covers_init_state(tmp_path):
    rng = random.Random(4)
    hist = random_history(rng, "cas-register", 4, 40, 0.0)
    e, st = cas_register_spec.encode(hist)
    ck = str(tmp_path / "frontier.npz")
    jax_wgl.check_encoded(cas_register_spec, e, st, chunk_iters=1,
                          timeout_s=0, checkpoint=ck)
    import numpy as np
    st2 = np.asarray(st).copy()
    st2[0] = st2[0] + 1
    # different init state: must not resume the stale frontier
    r = jax_wgl.check_encoded(cas_register_spec, e, st2)
    ck2 = str(tmp_path / "other.npz")
    r2 = jax_wgl.check_encoded(cas_register_spec, e, st2, checkpoint=ck2)
    assert r2["valid"] == r["valid"]


def test_batch_checkpoint_resume(tmp_path):
    """The batched keyshard path checkpoints mid-run: a timed-out
    multi-key check leaves a snapshot carrying the compacted frontier
    AND the already-decided keys; a rerun with the same arguments
    resumes and agrees with an uncheckpointed run (round-2 weak #5)."""
    import numpy as np
    from jepsen_tpu.parallel import check_batch_encoded

    rng = random.Random(7)
    hists = []
    for k in range(6):
        h = random_history(rng, "cas-register", 8, 150, 0.05)
        if k % 2 == 1:
            h = corrupt(rng, h)
            # clamp the corrupt read into the written range so the
            # state-abstraction pre-check can't decide it: these keys
            # must reach the search (and often exhaust slowly)
            for o in h:
                if o["type"] == "ok" and o["f"] == "read" \
                        and o.get("value") is not None:
                    o["value"] = o["value"] % 4
        hists.append(h)
    pairs = [cas_register_spec.encode(h) for h in hists]
    ck = str(tmp_path / "batch.npz")

    want = check_batch_encoded(cas_register_spec, pairs)
    r1 = check_batch_encoded(cas_register_spec, pairs, timeout_s=0,
                             chunk_iters=16, checkpoint=ck,
                             checkpoint_every_s=0)
    assert os.path.exists(ck), "snapshot written on timeout"
    assert any(r["valid"] == "unknown" for r in r1)
    # snapshot must carry the alive map + any harvested keys
    with np.load(ck) as data:
        assert "alive" in data.files and "hkeys" in data.files
    r2 = check_batch_encoded(cas_register_spec, pairs, chunk_iters=16,
                             checkpoint=ck)
    assert [r["valid"] for r in r2] == [r["valid"] for r in want]
    assert not os.path.exists(ck), "spent snapshot removed"


def test_batch_checkpoint_foreign_snapshot_ignored(tmp_path):
    from jepsen_tpu.parallel import check_batch_encoded
    rng = random.Random(9)
    p1 = [cas_register_spec.encode(
        random_history(rng, "cas-register", 4, 60, 0.05))]
    p2 = [cas_register_spec.encode(
        random_history(rng, "cas-register", 4, 60, 0.05))]
    ck = str(tmp_path / "batch.npz")
    check_batch_encoded(cas_register_spec, p1, timeout_s=0,
                        chunk_iters=1, checkpoint=ck)
    # a different batch at the same path must not resume from it
    r = check_batch_encoded(cas_register_spec, p2, checkpoint=ck)
    assert r[0]["valid"] in (True, False, "unknown")


def test_batch_checkpoint_survives_budget_change(tmp_path):
    """max_iters is not fingerprinted: a budget-exhausted batch snapshot
    resumes under a LARGER budget (advisor finding r3)."""
    from jepsen_tpu.parallel import check_batch_encoded
    rng = random.Random(11)
    h = corrupt(rng, random_history(rng, "cas-register", 8, 150, 0.05))
    for o in h:
        if o["type"] == "ok" and o["f"] == "read" \
                and o.get("value") is not None:
            o["value"] = o["value"] % 4
    pairs = [cas_register_spec.encode(h)]
    ck = str(tmp_path / "batch.npz")
    r1 = check_batch_encoded(cas_register_spec, pairs, max_configs=64,
                             chunk_iters=1, checkpoint=ck)
    if r1[0]["valid"] == "unknown":
        assert os.path.exists(ck)
        r2 = check_batch_encoded(cas_register_spec, pairs,
                                 checkpoint=ck)
        assert r2[0]["valid"] in (True, False)
        assert not os.path.exists(ck)
