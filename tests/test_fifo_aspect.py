"""FIFO aspect-checker differential tests: the polynomial bad-pattern
decision must agree exactly with the sequential WGL oracle wherever it
answers (it is used as an exact fast path, not a heuristic)."""

import random


from jepsen_tpu.checker import jax_wgl, wgl
from jepsen_tpu.models import fifo_queue_spec
from jepsen_tpu.models.queues import _fifo_fast_check
from jepsen_tpu.simulate import corrupt, random_history


def _decide(hist):
    e, st = fifo_queue_spec.encode(hist)
    inv32, ret32, _ = jax_wgl._encode_arrays(e)
    fast = _fifo_fast_check(e, inv32, ret32)
    if isinstance(fast, tuple):
        fast = fast[0]
    return e, st, fast


def test_differential_vs_oracle_many_seeds():
    agree = decided = 0
    for seed in range(60):
        rng = random.Random(seed)
        crash = 0.0 if seed % 2 == 0 else 0.08
        hist = random_history(rng, "fifo-queue", n_procs=4, n_ops=30,
                              crash_p=crash)
        if seed % 3 == 2:
            hist = corrupt(rng, hist)
        e, st, fast = _decide(hist)
        want = wgl.check_encoded(fifo_queue_spec, e, st)["valid"]
        if fast is not None:
            decided += 1
            assert fast == want, f"seed {seed}: aspect={fast} oracle={want}"
            agree += 1
    # info-free seeds must all be decided
    assert decided >= 20


def test_info_free_histories_always_decided():
    for seed in range(10):
        rng = random.Random(1000 + seed)
        hist = random_history(rng, "fifo-queue", n_procs=6, n_ops=40,
                              crash_p=0.0)
        _, _, fast = _decide(hist)
        assert fast is True


def test_big_valid_history_instant():
    rng = random.Random(45100)
    hist = random_history(rng, "fifo-queue", n_procs=16, n_ops=5000,
                          crash_p=0.0)
    e, st = fifo_queue_spec.encode(hist)
    r = jax_wgl.check_encoded(fifo_queue_spec, e, st)
    assert r["valid"] is True
    assert r["engine"] == "aspect"


def test_big_corrupt_history_instant():
    rng = random.Random(45100)
    hist = random_history(rng, "fifo-queue", n_procs=16, n_ops=5000,
                          crash_p=0.0)
    hist = corrupt(rng, hist)
    e, st = fifo_queue_spec.encode(hist)
    r = jax_wgl.check_encoded(fifo_queue_spec, e, st)
    assert r["valid"] is False
    assert r["engine"] == "aspect"


def test_info_dequeue_histories_decided_exactly():
    """Crashed dequeues no longer block the polynomial decision: the
    closure + threshold-matching extension decides them exactly (round-3
    upgrade; previously these fell to the NP-hard search)."""
    decided = 0
    for seed in range(30):
        rng = random.Random(3000 + seed)
        hist = random_history(rng, "fifo-queue", n_procs=4, n_ops=18,
                              crash_p=0.25)
        if not any(o["type"] == "info" and o["f"] == "dequeue"
                   for o in hist):
            continue
        e, st, fast = _decide(hist)
        assert fast is not None
        # bound the exponential oracle; unbounded 30-op crash-heavy
        # seeds cost minutes each (advisor finding r3)
        want = wgl.check_encoded(fifo_queue_spec, e, st,
                                 max_configs=300_000)["valid"]
        if want == "unknown":
            continue
        decided += 1
        assert fast == want
    assert decided >= 10


def _mk(events):
    """Build an indexed history from (kind, process, f, value) tuples."""
    from jepsen_tpu import history as h
    out = []
    for kind, p, f, v in events:
        out.append({"invoke": h.invoke_op, "ok": h.ok_op,
                    "info": h.info_op}[kind](p, f, v))
    return h.index(out)


def test_matching_feasible_info_dequeue_is_valid():
    # stuck value 1 is overtaken by ok-dequeued 2, but an info dequeue
    # invoked before deq(2) completes can have consumed it
    hist = _mk([("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
                ("invoke", 1, "enqueue", 2), ("ok", 1, "enqueue", 2),
                ("invoke", 2, "dequeue", None),
                ("invoke", 1, "dequeue", None),
                ("ok", 1, "dequeue", 2),
                ("info", 2, "dequeue", None)])
    e, st, fast = _decide(hist)
    assert fast is True
    assert wgl.check_encoded(fifo_queue_spec, e, st)["valid"] is True


def test_matching_late_info_dequeue_is_invalid():
    # the only info dequeue is invoked after deq(2) completed: it cannot
    # have consumed stuck value 1 before 2 left the queue
    hist = _mk([("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
                ("invoke", 1, "enqueue", 2), ("ok", 1, "enqueue", 2),
                ("invoke", 1, "dequeue", None),
                ("ok", 1, "dequeue", 2),
                ("invoke", 2, "dequeue", None),
                ("info", 2, "dequeue", None)])
    e, st = fifo_queue_spec.encode(hist)
    inv32, ret32, _ = jax_wgl._encode_arrays(e)
    from jepsen_tpu.models.queues import _fifo_fast_check
    fast = _fifo_fast_check(e, inv32, ret32)
    assert isinstance(fast, tuple) and fast[0] is False
    assert fast[1]["pattern"] == "dequeue-past-stuck-value"
    assert wgl.check_encoded(fifo_queue_spec, e, st)["valid"] is False


def test_matching_closure_needs_one_dequeue_per_value():
    # stuck 1 precedes stuck 2 which is overtaken by dequeued 3: the
    # closure forces BOTH to be consumed, so one info dequeue fails and
    # two (invoked in time) succeed
    base = [("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
            ("invoke", 0, "enqueue", 2), ("ok", 0, "enqueue", 2),
            ("invoke", 1, "enqueue", 3), ("ok", 1, "enqueue", 3),
            ("invoke", 2, "dequeue", None),
            ("invoke", 1, "dequeue", None),
            ("ok", 1, "dequeue", 3),
            ("info", 2, "dequeue", None)]
    one = _mk(base)
    e, st, fast = _decide(one)
    assert fast is False
    assert wgl.check_encoded(fifo_queue_spec, e, st)["valid"] is False
    two = _mk(base[:6]
              + [("invoke", 2, "dequeue", None),
                 ("invoke", 3, "dequeue", None),
                 ("invoke", 1, "dequeue", None),
                 ("ok", 1, "dequeue", 3),
                 ("info", 2, "dequeue", None),
                 ("info", 3, "dequeue", None)])
    e, st, fast = _decide(two)
    assert fast is True
    assert wgl.check_encoded(fifo_queue_spec, e, st)["valid"] is True


def test_adversarial_differential_with_info_dequeues():
    """Seeded slice of the round-3 adversarial fuzz (arbitrary dequeue
    returns, 25% crash rate): the aspect must agree with the oracle in
    both directions on every decided history."""
    from jepsen_tpu import history as h

    def adversarial(rng, n_procs, n_ops):
        hist, outstanding, values, done, nxt = [], {}, [], 0, 0
        while done < n_ops or outstanding:
            free = [p for p in range(n_procs) if p not in outstanding]
            if free and done < n_ops and (not outstanding
                                          or rng.random() < .6):
                p = rng.choice(free)
                if rng.random() < 0.5:
                    nxt += 1
                    inv = h.invoke_op(p, "enqueue", nxt)
                    values.append(nxt)
                else:
                    inv = h.invoke_op(p, "dequeue", None)
                outstanding[p] = inv
                hist.append(inv)
                done += 1
            else:
                p = rng.choice(list(outstanding))
                inv = outstanding.pop(p)
                r = rng.random()
                if r < 0.25:
                    hist.append(h.info_op(p, inv["f"], inv["value"]))
                elif inv["f"] == "enqueue":
                    hist.append(h.ok_op(p, "enqueue", inv["value"]))
                else:
                    v = rng.choice(values) if values and r < 0.9 \
                        else nxt + 100
                    hist.append(h.ok_op(p, "dequeue", v))
        return h.index(hist)

    n_valid = n_invalid = 0
    for seed in range(150):
        rng = random.Random(seed * 7 + 1)
        hist = adversarial(rng, 3, 8 + seed % 10)
        e, st, fast = _decide(hist)
        assert fast is not None
        want = wgl.check_encoded(fifo_queue_spec, e, st)["valid"]
        assert fast == want, f"seed {seed}: aspect={fast} oracle={want}"
        n_valid += want is True
        n_invalid += want is False
    assert n_valid >= 5 and n_invalid >= 50


def test_forced_search_scales_on_info_fifo():
    """With the witness-order hint + junk-enqueue prune, the raw device
    search (fast path disabled) decides info-bearing FIFO histories far
    beyond the old ~200-op ceiling, in a handful of rollout iterations."""
    import dataclasses
    forced = dataclasses.replace(fifo_queue_spec, fast_check=None)
    rng = random.Random(45100)
    hist = random_history(rng, "fifo-queue", n_procs=8, n_ops=600,
                          crash_p=0.05)
    e, st = forced.encode(hist)
    assert any(o["type"] == "info" and o["f"] == "dequeue" for o in hist)
    r = jax_wgl.check_encoded(forced, e, st, timeout_s=120)
    assert r["valid"] is True
    assert r["engine"] == "jax-wgl"
    assert r["iterations"] <= 64


def test_aspect_invalid_carries_witness():
    rng = random.Random(45100)
    hist = random_history(rng, "fifo-queue", n_procs=8, n_ops=200,
                          crash_p=0.0)
    hist = corrupt(rng, hist)
    e, st = fifo_queue_spec.encode(hist)
    r = jax_wgl.check_encoded(fifo_queue_spec, e, st)
    assert r["valid"] is False and r["engine"] == "aspect"
    assert "pattern" in r
    assert r["op"]["f"] == "dequeue"
    # confirm runs the oracle over the same history
    r2 = jax_wgl.check_encoded(fifo_queue_spec, e, st, confirm=True)
    assert r2["confirmed"] is True


def test_unordered_queue_fast_check_differential():
    """The bag fast check must agree with the oracle wherever it
    answers; FIFO-generated histories are valid bag histories too."""
    from jepsen_tpu.models import unordered_queue_spec
    from jepsen_tpu.models.queues import _unordered_fast_check
    decided = 0
    for seed in range(40):
        rng = random.Random(seed)
        crash = 0.0 if seed % 2 == 0 else 0.1
        hist = random_history(rng, "fifo-queue", n_procs=4, n_ops=24,
                              crash_p=crash)
        if seed % 3 == 2:
            hist = corrupt(rng, hist)
        e, st = unordered_queue_spec.encode(hist)
        inv32, ret32, _ = jax_wgl._encode_arrays(e)
        fast = _unordered_fast_check(e, inv32, ret32)
        if fast is None:
            continue
        if isinstance(fast, tuple):
            fast = fast[0]
        decided += 1
        want = wgl.check_encoded(unordered_queue_spec, e, st)["valid"]
        assert fast == want, f"seed {seed}: bag={fast} oracle={want}"
    assert decided >= 15


def test_crashed_enqueues_still_decided():
    """Only info DEQUEUES block a definite verdict: a history whose sole
    indeterminate ops are crashed enqueues decides exactly -- in both
    directions (corrupted variants cover the invalid side)."""
    found = invalid_seen = 0
    for seed in range(300):
        rng = random.Random(seed)
        hist = random_history(rng, "fifo-queue", n_procs=4, n_ops=24,
                              crash_p=0.1)
        infos = [o for o in hist if o["type"] == "info"]
        if not infos or any(o["f"] == "dequeue" for o in infos):
            continue
        if seed % 2 == 1:
            hist = corrupt(rng, hist)
        found += 1
        e, st, fast = _decide(hist)
        assert fast is not None
        want = wgl.check_encoded(fifo_queue_spec, e, st)["valid"]
        assert fast == want, f"seed {seed}"
        invalid_seen += want is False
        if found >= 10 and invalid_seen >= 2:
            break
    assert found >= 5 and invalid_seen >= 1


def test_bag_info_dequeues_decided():
    """The bag decision is now total on in-scope histories: crashed
    dequeues can always be completed as no-ops (no overtaking in a
    multiset), so the per-value scan alone decides."""
    from jepsen_tpu.models import unordered_queue_spec
    from jepsen_tpu.models.queues import _unordered_fast_check
    decided = 0
    for seed in range(20):
        rng = random.Random(5000 + seed)
        hist = random_history(rng, "unordered-queue", n_procs=4,
                              n_ops=24, crash_p=0.3)
        if not any(o["type"] == "info" and o["f"] == "dequeue"
                   for o in hist):
            continue
        e, st = unordered_queue_spec.encode(hist)
        inv32, ret32, _ = jax_wgl._encode_arrays(e)
        fast = _unordered_fast_check(e, inv32, ret32)
        assert fast is not None
        if isinstance(fast, tuple):
            fast = fast[0]
        decided += 1
        assert fast == wgl.check_encoded(unordered_queue_spec, e,
                                         st)["valid"]
    assert decided >= 8
