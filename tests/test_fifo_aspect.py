"""FIFO aspect-checker differential tests: the polynomial bad-pattern
decision must agree exactly with the sequential WGL oracle wherever it
answers (it is used as an exact fast path, not a heuristic)."""

import random

import pytest

from jepsen_tpu.checker import jax_wgl, wgl
from jepsen_tpu.models import fifo_queue_spec
from jepsen_tpu.models.queues import _fifo_fast_check
from jepsen_tpu.simulate import corrupt, random_history


def _decide(hist):
    e, st = fifo_queue_spec.encode(hist)
    inv32, ret32, _ = jax_wgl._encode_arrays(e)
    fast = _fifo_fast_check(e, inv32, ret32)
    if isinstance(fast, tuple):
        fast = fast[0]
    return e, st, fast


def test_differential_vs_oracle_many_seeds():
    agree = decided = 0
    for seed in range(60):
        rng = random.Random(seed)
        crash = 0.0 if seed % 2 == 0 else 0.08
        hist = random_history(rng, "fifo-queue", n_procs=4, n_ops=30,
                              crash_p=crash)
        if seed % 3 == 2:
            hist = corrupt(rng, hist)
        e, st, fast = _decide(hist)
        want = wgl.check_encoded(fifo_queue_spec, e, st)["valid"]
        if fast is not None:
            decided += 1
            assert fast == want, f"seed {seed}: aspect={fast} oracle={want}"
            agree += 1
    # info-free seeds must all be decided
    assert decided >= 20


def test_info_free_histories_always_decided():
    for seed in range(10):
        rng = random.Random(1000 + seed)
        hist = random_history(rng, "fifo-queue", n_procs=6, n_ops=40,
                              crash_p=0.0)
        _, _, fast = _decide(hist)
        assert fast is True


def test_big_valid_history_instant():
    rng = random.Random(45100)
    hist = random_history(rng, "fifo-queue", n_procs=16, n_ops=5000,
                          crash_p=0.0)
    e, st = fifo_queue_spec.encode(hist)
    r = jax_wgl.check_encoded(fifo_queue_spec, e, st)
    assert r["valid"] is True
    assert r["engine"] == "aspect"


def test_big_corrupt_history_instant():
    rng = random.Random(45100)
    hist = random_history(rng, "fifo-queue", n_procs=16, n_ops=5000,
                          crash_p=0.0)
    hist = corrupt(rng, hist)
    e, st = fifo_queue_spec.encode(hist)
    r = jax_wgl.check_encoded(fifo_queue_spec, e, st)
    assert r["valid"] is False
    assert r["engine"] == "aspect"


def test_info_histories_fall_back_to_search():
    rng = random.Random(3)
    hist = random_history(rng, "fifo-queue", n_procs=4, n_ops=30,
                          crash_p=0.2)
    e, st, fast = _decide(hist)
    if fast is None:
        r = jax_wgl.check_encoded(fifo_queue_spec, e, st)
        assert r["engine"] == "jax-wgl"
        assert r["valid"] == wgl.check_encoded(
            fifo_queue_spec, e, st)["valid"]


def test_aspect_invalid_carries_witness():
    rng = random.Random(45100)
    hist = random_history(rng, "fifo-queue", n_procs=8, n_ops=200,
                          crash_p=0.0)
    hist = corrupt(rng, hist)
    e, st = fifo_queue_spec.encode(hist)
    r = jax_wgl.check_encoded(fifo_queue_spec, e, st)
    assert r["valid"] is False and r["engine"] == "aspect"
    assert "pattern" in r
    assert r["op"]["f"] == "dequeue"
    # confirm runs the oracle over the same history
    r2 = jax_wgl.check_encoded(fifo_queue_spec, e, st, confirm=True)
    assert r2["confirmed"] is True


def test_unordered_queue_fast_check_differential():
    """The bag fast check must agree with the oracle wherever it
    answers; FIFO-generated histories are valid bag histories too."""
    from jepsen_tpu.models import unordered_queue_spec
    from jepsen_tpu.models.queues import _unordered_fast_check
    decided = 0
    for seed in range(40):
        rng = random.Random(seed)
        crash = 0.0 if seed % 2 == 0 else 0.1
        hist = random_history(rng, "fifo-queue", n_procs=4, n_ops=24,
                              crash_p=crash)
        if seed % 3 == 2:
            hist = corrupt(rng, hist)
        e, st = unordered_queue_spec.encode(hist)
        inv32, ret32, _ = jax_wgl._encode_arrays(e)
        fast = _unordered_fast_check(e, inv32, ret32)
        if fast is None:
            continue
        if isinstance(fast, tuple):
            fast = fast[0]
        decided += 1
        want = wgl.check_encoded(unordered_queue_spec, e, st)["valid"]
        assert fast == want, f"seed {seed}: bag={fast} oracle={want}"
    assert decided >= 15


def test_crashed_enqueues_still_decided():
    """Only info DEQUEUES block a definite verdict: a history whose sole
    indeterminate ops are crashed enqueues decides exactly -- in both
    directions (corrupted variants cover the invalid side)."""
    found = invalid_seen = 0
    for seed in range(300):
        rng = random.Random(seed)
        hist = random_history(rng, "fifo-queue", n_procs=4, n_ops=24,
                              crash_p=0.1)
        infos = [o for o in hist if o["type"] == "info"]
        if not infos or any(o["f"] == "dequeue" for o in infos):
            continue
        if seed % 2 == 1:
            hist = corrupt(rng, hist)
        found += 1
        e, st, fast = _decide(hist)
        assert fast is not None
        want = wgl.check_encoded(fifo_queue_spec, e, st)["valid"]
        assert fast == want, f"seed {seed}"
        invalid_seen += want is False
        if found >= 10 and invalid_seen >= 2:
            break
    assert found >= 5 and invalid_seen >= 1
