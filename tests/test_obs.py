"""The unified observability layer: tracer, metrics, persistence, and
the instrumentation wired through core/interpreter/nemesis/control/
checker and the device WGL search."""

import contextvars
import json
import pathlib
import random
import threading

import pytest

from jepsen_tpu import core, generator as gen, obs, store
from jepsen_tpu import tests as tst
from jepsen_tpu.generator import testing as gtest
from jepsen_tpu.obs import metrics as obs_metrics
from jepsen_tpu.tests import Atom


@pytest.fixture(autouse=True)
def store_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))


def dummy_test(**kw):
    t = tst.noop_test()
    t["ssh"] = {"dummy?": True}
    t.update(kw)
    return t


# ---------------------------------------------------------------------------
# tracer

def test_off_by_default():
    """No sinks bound -> every facade call is a no-op (the <5%-or-off
    acceptance criterion: instrumented hot paths pay one global read)."""
    assert not obs.enabled()
    assert obs.tracer() is None and obs.registry() is None
    # none of these may raise or record anything
    with obs.span("x"):
        obs.instant("i")
        obs.complete("c", 0, 10)
        obs.inc("n")
        obs.observe("h", 0.1)
    assert not obs.enabled()


def test_span_nesting_and_thread_propagation():
    """Span parentage flows through contextvars snapshots -- the same
    mechanism the interpreter's worker spawn uses -- so a span opened on
    a worker thread records the spawning scope's span as its parent."""
    tr = obs.Tracer()
    inner_parent = {}
    with obs.bind(tr, None):
        with tr.span("outer"):
            assert obs.current_span() == "outer"
            ctx = contextvars.copy_context()

            def worker():
                inner_parent["before"] = obs.current_span()
                with tr.span("inner"):
                    pass

            t = threading.Thread(target=ctx.run, args=(worker,))
            t.start()
            t.join()
    evs = {e["name"]: e for e in tr.events() if e["ph"] == "X"}
    assert inner_parent["before"] == "outer"
    assert evs["inner"]["args"]["parent"] == "outer"
    assert "parent" not in (evs["outer"].get("args") or {})
    # the inner span ran on a different OS thread: distinct tids
    assert evs["inner"]["tid"] != evs["outer"]["tid"]


def test_trace_dump_is_chrome_trace_loadable(tmp_path):
    """trace.jsonl must parse BOTH as the Chrome trace JSON array format
    (leading '[', trailing commas, ']' optional) and line-by-line."""
    tr = obs.Tracer()
    with tr.span("phase", args={"k": 1}):
        tr.instant("marker", cat="search")
    tr.counter("frontier", {"depth": 3})
    p = tr.dump(str(tmp_path / "trace.jsonl"))

    text = pathlib.Path(p).read_text()
    assert text.startswith("[\n")
    # chrome://tracing's parser: complete the array and load it whole
    # (trace_meta is the wall-clock/context anchor obs.merge keys on)
    whole = json.loads(text.rstrip().rstrip(",") + "]")
    assert {e["name"] for e in whole} == {"trace_meta", "phase",
                                          "marker", "frontier"}
    for e in whole:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(e)
    # line-by-line (jq/grep style) via the tolerant loader
    evs = obs.load_trace(p)
    assert len(evs) == 4
    assert obs.trace_meta(evs)["epoch_ns"] > 0
    x = [e for e in evs if e["ph"] == "X"][0]
    assert x["dur"] >= 0


def test_tracer_event_cap(tmp_path):
    tr = obs.Tracer(max_events=3)
    for i in range(10):
        tr.instant(f"e{i}")
    assert len(tr.events()) == 3
    assert tr.dropped == 7
    # truncation is recorded IN the dumped file, not silent
    evs = obs.load_trace(tr.dump(str(tmp_path / "t.jsonl")))
    marker = [e for e in evs if e["name"] == "trace_truncated"]
    assert marker and marker[0]["args"]["dropped_events"] == 7


# ---------------------------------------------------------------------------
# metrics

def test_histogram_bucket_math():
    h = obs_metrics.Histogram(bounds=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.001, 0.005, 0.05, 99.0):
        h.observe(v)
    # per-bucket (non-cumulative) counts; one overflow bucket
    assert h.counts == [2, 1, 1, 1]
    assert h.count == 5
    assert h.sum == pytest.approx(0.0565 + 99.0)
    assert h.min == 0.0005 and h.max == 99.0
    assert h.quantile(0.5) == 0.01
    assert h.quantile(0.99) == 99.0       # overflow reports the max
    d = h.to_dict()
    assert d["buckets_le"] == [0.001, 0.01, 0.1]
    assert len(d["counts"]) == len(d["buckets_le"]) + 1
    assert obs_metrics.Histogram().quantile(0.5) is None


def test_registry_labels_and_snapshot():
    reg = obs.Registry()
    reg.inc("ops", f="read")
    reg.inc("ops", 2, f="read")
    reg.inc("ops", f="write")
    reg.set_gauge("depth", 7)
    reg.max_gauge("depth_max", 3)
    reg.max_gauge("depth_max", 9)
    reg.max_gauge("depth_max", 5)
    reg.observe("lat", 0.002)
    snap = reg.snapshot()
    assert snap["counters"]["ops{f=read}"] == 3
    assert snap["counters"]["ops{f=write}"] == 1
    assert snap["gauges"]["depth"] == 7
    assert snap["gauges"]["depth_max"] == 9
    assert snap["histograms"]["lat"]["count"] == 1
    # snapshot is plain JSON
    json.dumps(snap)


def test_metrics_snapshot_roundtrip_through_store(tmp_path):
    """The store encoder must serialize snapshots containing numpy
    scalars/arrays and Path values without call-site casts (the
    satellite fix: np.bool_ and pathlib.Path used to fall back to
    repr strings)."""
    np = pytest.importorskip("numpy")
    reg = obs.Registry()
    reg.inc("explored", np.int64(42))
    reg.set_gauge("load", np.float32(0.5))
    reg.set_gauge("dropped", np.bool_(False))
    reg.set_gauge("shards", np.array([3, 1]))
    reg.set_gauge("dir", pathlib.Path("/tmp/x"))
    p = str(tmp_path / "metrics.json")
    store._dump_json(reg.snapshot(), p)
    back = json.load(open(p))
    assert back["counters"]["explored"] == 42
    assert back["gauges"]["load"] == 0.5
    assert back["gauges"]["dropped"] is False
    assert back["gauges"]["shards"] == [3, 1]
    assert back["gauges"]["dir"] == "/tmp/x"


# ---------------------------------------------------------------------------
# generator.trace -> tracer (one event stream, not two)

def test_generator_trace_routes_through_tracer(caplog):
    import logging
    tr = obs.Tracer()
    g = gen.trace("tag", gen.limit(2, gen.repeat({"f": "read"})))
    with obs.bind(tr, None), caplog.at_level(logging.INFO):
        hist = gtest.quick(g)
    assert len([o for o in hist if o["type"] == "invoke"]) == 2
    evs = [e for e in tr.events() if e["name"] == "gen.tag"]
    kinds = {e["args"]["kind"] for e in evs}
    assert {"op", "update"} <= kinds
    # the original logging behavior is preserved alongside
    assert any("tag op ->" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# full-run wiring: lifecycle spans, op spans, nemesis windows, control
# spans, checker spans, persisted artifacts

class _WindowNemesis:
    """Minimal nemesis with a start/stop fault window."""

    def setup(self, test):
        return self

    def invoke(self, test, op):
        out = dict(op)
        out["type"] = "info"
        out["value"] = "zap"
        return out

    def teardown(self, test):
        pass

    def fs(self):
        return {"start", "stop"}


def _run_dummy(name, **kw):
    import jepsen_tpu.nemesis as jnemesis

    class N(_WindowNemesis, jnemesis.Nemesis):
        pass

    state = Atom(None)
    rng = random.Random(45100)
    t = dummy_test(
        name=name,
        db=tst.atom_db(state),
        client=tst.atom_client(state),
        nemesis=N(),
        concurrency=4,
        generator=gen.phases(
            gen.nemesis(gen.limit(1, {"f": "start"})),
            gen.clients(gen.limit(30, gen.mix([
                lambda: {"f": "read"},
                lambda: {"f": "write", "value": rng.randint(0, 4)},
            ]))),
            gen.nemesis(gen.limit(1, {"f": "stop"})),
        ),
        **kw,
    )
    return core.run(t)


def _store_file(test, name):
    return pathlib.Path(store.path(test, name))


def test_run_writes_trace_and_metrics_with_lifecycle_phases():
    test = _run_dummy("obs-smoke")
    trace_path = _store_file(test, "trace.jsonl")
    metrics_path = _store_file(test, "metrics.json")
    assert trace_path.exists() and metrics_path.exists()

    evs = obs.load_trace(str(trace_path))
    spans = {e["name"] for e in evs if e["ph"] == "X"
             and e.get("cat") == "lifecycle"}
    # the run lifecycle is fully traced
    assert {"jepsen.run", "client-nemesis.setup", "run-case",
            "analyze", "client-nemesis.teardown"} <= spans
    # root span wraps everything: jepsen.run has no parent, analyze does
    by_name = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert by_name["analyze"]["args"]["parent"] == "jepsen.run"
    assert "parent" not in (by_name["jepsen.run"].get("args") or {})

    # per-op invoke->complete spans on logical-worker tracks
    ops = [e for e in evs if e.get("cat") == "op" and e["ph"] == "X"]
    assert len(ops) >= 30
    assert {e["args"]["type"] for e in ops} <= {"ok", "fail", "info"}
    assert all(isinstance(e["tid"], int) for e in ops)

    # nemesis invocation spans + one open/close fault window pair
    nem = [e for e in evs if e.get("cat") == "nemesis"]
    assert {e["ph"] for e in nem} >= {"X", "b", "e"}
    b = [e for e in nem if e["ph"] == "b"][0]
    e_ = [e for e in nem if e["ph"] == "e"][0]
    assert b["id"] == e_["id"]

    # checker spans carry the verdict
    checks = [e for e in evs if e.get("cat") == "checker"]
    assert checks and any(c["args"]["valid"] == "True" for c in checks)

    # metrics: op counts + latency histograms persisted as plain JSON
    m = json.loads(metrics_path.read_text())
    done = {k: v for k, v in m["counters"].items()
            if k.startswith("interpreter.ops_completed")}
    assert sum(done.values()) >= 30
    lat = m["histograms"]["interpreter.op_latency_s"]
    assert lat["count"] >= 30 and lat["sum"] > 0
    assert m["counters"]["nemesis.ops{f=start}"] == 1
    assert m["counters"]["nemesis.faults_started"] == 1
    ck = {k: v for k, v in m["counters"].items()
          if k.startswith("checker.checks")}
    assert ck

    # after the run the process-global binding is gone
    assert not obs.enabled()


def test_crashed_run_still_writes_artifacts():
    """A crashed run is exactly the one whose trace matters: artifacts
    persist from the finally path, and the obs handles are released."""
    from jepsen_tpu import db as jdb

    class BadDB(jdb.DB):
        def setup(self, test, node):
            raise RuntimeError("boom")

    t = dummy_test(name="obs-crash", db=BadDB())
    with pytest.raises(RuntimeError, match="boom"):
        core.run(t)
    # core.run worked on a prepare_test COPY of t; find the run dir on
    # disk (exactly how a human would after a crash)
    runs = list((pathlib.Path(store.base_dir) / "obs-crash").iterdir())
    runs = [d for d in runs if d.is_dir() and not d.is_symlink()]
    assert len(runs) == 1
    trace_path = runs[0] / "trace.jsonl"
    assert trace_path.exists()
    assert (runs[0] / "metrics.json").exists()
    evs = obs.load_trace(str(trace_path))
    spans = {e["name"] for e in evs if e["ph"] == "X"}
    # the root span closed through the unwinding context managers
    assert "jepsen.run" in spans
    assert not obs.enabled()


def test_obs_opt_out():
    test = _run_dummy("obs-off", **{"obs?": False})
    assert test["results"]["valid"] is True
    assert not _store_file(test, "trace.jsonl").exists()
    assert not _store_file(test, "metrics.json").exists()
    assert "obs" not in test


def test_control_exec_spans():
    """Remote exec/upload chokepoints trace per-call spans (dummy
    transport -- same code path every real transport takes)."""
    from jepsen_tpu import control as c
    tr, reg = obs.Tracer(), obs.Registry()
    test = {"nodes": ["n1"], "ssh": {"dummy?": True}}
    with obs.bind(tr, reg):
        with core.with_sessions(test):
            with c.on("n1"):
                c.exec_("echo", "hi")
    evs = [e for e in tr.events() if e.get("cat") == "control"]
    assert evs and evs[0]["name"] == "control.exec"
    assert evs[0]["args"]["host"] == "n1"
    assert "echo" in evs[0]["args"]["cmd"]
    assert reg.counter_value("control.remote_calls", op="exec") == 1
    assert reg.histogram("control.remote_s", op="exec").count == 1


def test_run_with_jax_wgl_search_telemetry():
    """The acceptance run: a local run whose checker drives the device
    WGL engine produces metrics.json with search telemetry (states
    explored, chunk count) and heartbeat events in trace.jsonl."""
    from jepsen_tpu.checker import checkers as ck
    state = Atom(None)
    rng = random.Random(7)
    t = dummy_test(
        name="obs-wgl",
        db=tst.atom_db(state),
        client=tst.atom_client(state),
        concurrency=3,
        # pin the flat single-search path: this test asserts the
        # engine=jax-wgl telemetry shape, and whether the search
        # planner finds a sealed cut (rerouting through the batch
        # engine, engine=jax-wgl-batch) depends on live-run timing
        **{"searchplan?": False},
        generator=gen.clients(gen.limit(24, gen.mix([
            lambda: {"f": "read"},
            lambda: {"f": "write", "value": rng.randint(0, 3)},
            lambda: {"f": "cas", "value": [rng.randint(0, 3),
                                           rng.randint(0, 3)]},
        ]))),
        # the AtomDB resets the register to 0, so the model starts
        # there too (init-ops) -- otherwise a read dispatched before
        # the first write observes 0 and the verdict flaps with the
        # unseeded generator shuffle
        checker=ck.linearizable({"model": "cas-register",
                                 "algorithm": "jax-wgl",
                                 "init-ops": [{"f": "write",
                                               "value": 0}]}),
    )
    test = core.run(t)
    assert test["results"]["valid"] is True, test["results"]

    m = json.loads(_store_file(test, "metrics.json").read_text())
    # chunk count: at least one device dispatch was heartbeat-counted
    assert m["counters"]["wgl.chunks{engine=jax-wgl}"] >= 1
    assert m["counters"]["wgl.searches{engine=jax-wgl}"] == 1
    assert m["counters"]["wgl.states_explored_total{engine=jax-wgl}"] >= 0
    assert "wgl.states_explored{engine=jax-wgl}" in m["gauges"]
    assert "wgl.table_load{engine=jax-wgl}" in m["gauges"]
    assert m["histograms"]["wgl.chunk_s{engine=jax-wgl}"]["count"] >= 1

    evs = obs.load_trace(str(_store_file(test, "trace.jsonl")))
    hb = [e for e in evs if e["name"] == "wgl.heartbeat.jax-wgl"]
    assert hb and {"iteration", "frontier", "explored",
                   "chunk_s"} <= set(hb[0]["args"])
    done = [e for e in evs if e["name"] == "wgl.done.jax-wgl"]
    assert done and done[0]["args"]["valid"] == "True"
    # counter tracks render frontier/explored as Perfetto series
    assert any(e["ph"] == "C" and e["name"] == "wgl.jax-wgl"
               for e in evs)


def test_search_session_pins_sinks_at_capture():
    """A search captures its sinks ONCE at start: an engine thread the
    checker competition abandoned (joined with timeout=0.5) must not
    write phantom heartbeats into the NEXT run's artifacts."""
    from jepsen_tpu.obs import search as obs_search
    tr_a, reg_a = obs.Tracer(), obs.Registry()
    with obs.bind(tr_a, reg_a):
        so = obs_search.capture()
        so.heartbeat("jax-wgl", iteration=1, chunk_s=0.1, frontier=5)
    # run A is over, run B binds fresh sinks; the straggler keeps going
    tr_b, reg_b = obs.Tracer(), obs.Registry()
    with obs.bind(tr_b, reg_b):
        so.heartbeat("jax-wgl", iteration=2, chunk_s=0.1, frontier=3)
        so.summary("jax-wgl", {"valid": True, "configs_explored": 9})
    # everything landed in A's sinks, nothing in B's
    assert reg_a.counter_value("wgl.chunks", engine="jax-wgl") == 2
    assert reg_a.counter_value("wgl.searches", engine="jax-wgl") == 1
    assert reg_b.snapshot() == {"counters": {}, "gauges": {},
                                "histograms": {}}
    assert tr_b.events() == []
    assert len([e for e in tr_a.events()
                if e["name"] == "wgl.heartbeat.jax-wgl"]) == 2
    # and a session captured while nothing is bound stays a no-op
    so_off = obs_search.capture()
    assert not so_off.enabled()
    so_off.heartbeat("jax-wgl", iteration=1, chunk_s=0.1)


def test_web_home_page_links_obs_artifacts():
    """The web UI's home page lists each run's trace/metrics artifacts
    (served by the existing /files handler)."""
    import urllib.parse

    from jepsen_tpu import web
    test = _run_dummy("obs-web")
    page = web._home_page()
    quoted = urllib.parse.quote(test["start-time"])
    assert "Observability" in page
    assert f"{quoted}/trace.jsonl" in page
    assert f"{quoted}/metrics.json" in page


def test_obs_in_test_map_is_not_serialized():
    test = _run_dummy("obs-noser")
    t = store.serializable_test(test)
    assert "obs" not in t
    # and test.json on disk parses cleanly
    loaded = store.load(test["name"], test["start-time"])
    assert loaded["results"]["valid"] is True
