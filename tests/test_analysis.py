"""Static-diagnostics subsystem tests: the shared Diagnostic
model, histlint over corrupted histories (each defect class -> its
code), planlint over broken plans, codelint over seeded thread-safety
defects, the tools/lint.py driver's exit codes, and the core.run /
checker / store / obs integration points."""

import json
import os
import subprocess
import sys

import pytest

from jepsen_tpu import analysis
from jepsen_tpu import checker as jchecker
from jepsen_tpu import core
from jepsen_tpu import generator as gen
from jepsen_tpu import history as h
from jepsen_tpu import obs
from jepsen_tpu import store
from jepsen_tpu import tests as tst
from jepsen_tpu.analysis import codelint, histlint, planlint
from jepsen_tpu.checker import checkers as ck
from jepsen_tpu.tests import Atom

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def store_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setattr(store, "base_dir", str(tmp_path / "store"))


def codes(diags):
    return [d.code for d in diags]


def error_codes(diags):
    return [d.code for d in analysis.errors(diags)]


# ---------------------------------------------------------------------------
# diagnostics model

def test_diagnostic_model_and_renderers():
    d1 = analysis.diag("HL002", analysis.ERROR, "boom", "history[3]",
                       "fix it")
    d2 = analysis.diag("HL001", analysis.WARNING, "meh")
    assert analysis.max_severity([d1, d2]) == "error"
    assert analysis.max_severity([d2]) == "warning"
    assert analysis.max_severity([]) is None
    assert analysis.severity_counts([d1, d2]) == {
        "error": 1, "warning": 1, "info": 0}
    text = analysis.render_text([d2, d1], title="report:")
    # worst first, code + location + hint all present
    assert text.index("HL002") < text.index("HL001")
    assert "history[3]" in text and "fix: fix it" in text
    j = analysis.to_json([d1])
    assert j["counts"]["error"] == 1
    assert j["diagnostics"][0]["code"] == "HL002"
    # round-trips through the store encoder
    json.dumps(j)


def test_run_analyzer_emits_obs_span_and_counter():
    from jepsen_tpu.obs import Registry, Tracer
    tr, reg = Tracer(), Registry()
    with obs.bind(tr, reg):
        out = analysis.run_analyzer(
            "histlint", lambda: [analysis.diag("HL004", analysis.ERROR,
                                               "x")])
    assert codes(out) == ["HL004"]
    names = {e.get("name") for e in tr.events()}
    assert "analysis.histlint" in names
    counters = reg.snapshot()["counters"]
    assert counters[
        "analysis.diagnostics{analyzer=histlint,severity=error}"] == 1


# ---------------------------------------------------------------------------
# histlint: each defect class -> its specific code

def valid_history():
    return h.parse_history_edn_like([
        ("invoke", 0, "write", 1),
        ("invoke", 1, "read", None),
        ("ok", 0, "write", 1),
        ("ok", 1, "read", 1),
        ("invoke", 0, "cas", [1, 2]),
        ("fail", 0, "cas", [1, 2]),
        ("invoke", 1, "read", None),
        ("info", 1, "read", None),
    ])


def test_histlint_clean_history():
    assert histlint.lint_history(valid_history()) == []


def test_histlint_dangling_invoke():
    hist = valid_history()[:-1]   # drop the final info completion
    diags = histlint.lint_history(hist)
    assert codes(diags) == ["HL001"]
    assert diags[0].severity == "warning"


def test_histlint_overlapping_invocations():
    hist = valid_history()
    # process 0 invokes again while its cas (invoked at 4) is open
    hist.insert(5, h.op("invoke", 0, "read", None))
    diags = histlint.lint_history(h.index(hist))
    assert "HL002" in error_codes(diags)


def test_histlint_completion_without_invoke():
    hist = h.index([h.op("ok", 3, "read", 7)])
    assert error_codes(histlint.lint_history(hist)) == ["HL003"]
    # ...but a bare nemesis info event is legal
    nem = h.index([h.op("info", "nemesis", "start", None)])
    assert histlint.lint_history(nem) == []


def test_histlint_mismatched_completion_f():
    hist = h.index([h.op("invoke", 0, "write", 1),
                    h.op("ok", 0, "read", 1)])
    assert error_codes(histlint.lint_history(hist)) == ["HL003"]


def test_histlint_unknown_type():
    hist = h.index([h.op("explode", 0, "read", None)])
    assert error_codes(histlint.lint_history(hist)) == ["HL004"]


def test_histlint_nonmonotonic_index():
    hist = valid_history()
    hist[3]["index"] = 1   # duplicate of an earlier index
    diags = histlint.lint_history(hist)
    assert "HL005" in error_codes(diags)


def test_histlint_unknown_op_f():
    diags = histlint.lint_history(
        valid_history(), model_fs={"read", "write"})
    # once per op (the invoke), not once per event of the pair
    assert error_codes(diags) == ["HL006"]
    assert "cas" in diags[0].message


def test_histlint_missing_fields_and_non_mapping():
    diags = histlint.lint_history(
        [{"type": "invoke"}, 42, {"type": "ok", "process": None}])
    assert error_codes(diags) == ["HL007", "HL007", "HL007"]


def test_histlint_encoded_tensors():
    from jepsen_tpu.models import base as mbase
    spec = mbase.model_spec("cas-register")
    e, _ = spec.encode(valid_history())
    assert histlint.lint_encoded(e) == []
    # corrupt: first row returns before it invokes
    e.return_idx[0] = e.invoke_idx[0] - 1
    assert "HL010" in codes(histlint.lint_encoded(e))
    # corrupt: ok row never returns
    e2, _ = spec.encode(valid_history())
    e2.return_idx[e2.is_ok.argmax()] = h.INF_TIME
    assert "HL012" in codes(histlint.lint_encoded(e2))
    # corrupt: unsorted rows
    e3, _ = spec.encode(valid_history())
    e3.invoke_idx[0], e3.invoke_idx[1] = e3.invoke_idx[1], \
        e3.invoke_idx[0]
    assert "HL011" in codes(histlint.lint_encoded(e3))


def test_model_op_set_walks_checkers():
    checker = jchecker.compose({
        "lin": ck.linearizable({"model": "cas-register"}),
        "noop": jchecker.noop(),
    })
    fs = histlint.model_op_set({"checker": checker})
    assert fs == {"read", "write", "cas"}
    assert histlint.model_op_set({"checker": jchecker.noop()}) is None


# ---------------------------------------------------------------------------
# history hardening (satellite): HistoryError names process/index

def test_pairs_raises_history_error_on_overlap():
    hist = h.index([h.op("invoke", 2, "read", None),
                    h.op("invoke", 2, "write", 1)])
    with pytest.raises(h.HistoryError) as ei:
        h.pairs(hist)
    assert ei.value.process == 2
    assert ei.value.index == 1
    assert "single-threaded" in str(ei.value)


def test_ensure_indexed_raises_on_non_mapping():
    with pytest.raises(h.HistoryError) as ei:
        h.ensure_indexed([h.op("invoke", 0, "read", None), "nope"])
    assert ei.value.index == 1
    assert "not a mapping" in str(ei.value)


def test_checker_turns_malformed_history_into_unknown():
    """A history that pairs() rejects must not crash check_safe: the
    verdict degrades to unknown, and histlint has flagged HL002."""
    hist = h.index([h.op("invoke", 0, "read", None),
                    h.op("invoke", 0, "write", 1),
                    h.op("ok", 0, "write", 1)])
    test = {"checker": ck.linearizable({"model": "cas-register"})}
    res = jchecker.check_safe(test["checker"], test, hist)
    assert res["valid"] == "unknown"
    report = test["analysis"]["history"]
    assert any(d["code"] == "HL002"
               for d in report["diagnostics"])


# ---------------------------------------------------------------------------
# planlint

def good_plan(**kw):
    t = tst.noop_test()
    t["ssh"] = {"dummy?": True}
    t.update(kw)
    return core.prepare_test(t)


def test_planlint_clean_plan():
    assert analysis.errors(planlint.lint_plan(good_plan())) == []


def test_planlint_missing_client():
    t = good_plan()
    del t["client"]
    assert "PL001" in error_codes(planlint.lint_plan(t))


def test_planlint_bad_nemesis_and_checker():
    t = good_plan(nemesis=object())
    assert "PL003" in error_codes(planlint.lint_plan(t))
    t2 = good_plan(checker=object())
    assert "PL004" in error_codes(planlint.lint_plan(t2))


def test_planlint_bad_generator_type():
    t = good_plan(generator=1234)
    assert "PL005" in error_codes(planlint.lint_plan(t))


def test_planlint_concurrency():
    t = good_plan(concurrency=-3)
    assert "PL006" in error_codes(planlint.lint_plan(t))
    t2 = good_plan(concurrency=3)   # 5 nodes
    assert "PL007" in codes(planlint.lint_plan(t2))


def test_planlint_generator_op_outside_model():
    t = good_plan(
        checker=ck.linearizable({"model": "cas-register"}),
        generator=gen.clients(gen.limit(3, gen.repeat(
            {"f": "increment", "value": 1}))))
    diags = planlint.lint_plan(t)
    assert "PL008" in error_codes(diags)
    # supported f's pass
    t2 = good_plan(
        checker=ck.linearizable({"model": "cas-register"}),
        generator=gen.clients(gen.limit(3, gen.repeat({"f": "read"}))))
    assert "PL008" not in codes(planlint.lint_plan(t2))


def test_planlint_preflight_raises_on_fatal():
    t = good_plan()
    del t["client"]
    with pytest.raises(planlint.PlanLintError) as ei:
        planlint.preflight(t)
    assert any(d.code == "PL001" for d in ei.value.diagnostics)


def test_core_run_preflight_rejects_broken_plan():
    t = good_plan(name="preflight-reject", generator=1234)
    with pytest.raises(planlint.PlanLintError):
        core.run(t)
    # opt-out runs (and completes: generator 1234 is simply unusable,
    # so use None instead to keep the run green)
    t2 = good_plan(name="preflight-optout", generator=None)
    t2["preflight?"] = False
    done = core.run(t2)
    assert "plan" not in (done.get("analysis") or {})


# ---------------------------------------------------------------------------
# end-to-end: a clean tier-1-style workload has zero error diagnostics,
# analysis.json is persisted, and the web UI links it

def test_clean_workload_run_zero_error_diagnostics():
    state = Atom(None)
    t = good_plan(
        name="analysis-clean",
        db=tst.atom_db(state),
        client=tst.atom_client(state),
        concurrency=4,
        checker=ck.linearizable({"model": "cas-register",
                                 "algorithm": "wgl",
                                 "init-ops": [{"f": "write",
                                               "value": 0}]}),
        generator=gen.clients(gen.limit(30, gen.mix(
            [gen.repeat({"f": "read"}),
             gen.repeat({"f": "write", "value": 2})]))),
    )
    done = core.run(t)
    assert done["results"]["valid"] is True
    report = done["analysis"]["history"]
    assert report["counts"]["error"] == 0
    plan_report = done["analysis"]["plan"]
    assert plan_report["counts"]["error"] == 0
    # persisted next to results.json
    p = store.path(done, "analysis.json")
    assert os.path.exists(p)
    with open(p) as f:
        on_disk = json.load(f)
    assert on_disk["history"]["counts"]["error"] == 0
    # metrics carry the analyzer counters
    with open(store.path(done, "metrics.json")) as f:
        metrics = json.load(f)
    assert any(k.startswith("analysis.run_s")
               for k in metrics["histograms"])
    assert any(k.startswith("analysis.diagnostics")
               for k in metrics["counters"])
    # the web home page links the analysis artifact
    from jepsen_tpu import web
    rows = web._fast_tests()
    assert any("analysis.json" in r["obs"] for r in rows)


def test_analysis_opt_out_per_test():
    hist = valid_history()
    test = {"analysis?": False,
            "checker": jchecker.unbridled_optimism()}
    jchecker.check_safe(test["checker"], test, hist)
    assert "analysis" not in test


def test_corrupted_workload_run_flags_errors():
    """core.run on a history with a corrupt checker-visible structure:
    the verdict is computed (checkers are fault-tolerant) but
    analysis.json records the defect."""
    hist = valid_history()
    hist.insert(2, h.op("invoke", 0, "read", None))   # overlap on p0
    test = {"name": "analysis-corrupt",
            "start-time": store.local_time(),
            "checker": jchecker.unbridled_optimism(),
            "history": h.index(hist)}
    core.analyze(test)
    report = test["analysis"]["history"]
    assert any(d["code"] == "HL002" for d in report["diagnostics"])
    with open(store.path(test, "analysis.json")) as f:
        assert json.load(f)["history"]["counts"]["error"] >= 1


# ---------------------------------------------------------------------------
# codelint

SEEDED_DEFECT = '''
import threading

_cache = {}
_lock = threading.Lock()


def worker(key, value):
    _cache[key] = value          # unsynchronized!


def safe(key, value):
    with _lock:
        _cache[key] = value


def spawn():
    threading.Thread(target=worker, args=(1, 2)).start()
'''


def test_codelint_catches_seeded_defect(tmp_path):
    p = tmp_path / "defect.py"
    p.write_text(SEEDED_DEFECT)
    diags = codelint.lint_paths([str(p)])
    assert error_codes(diags) == ["CL001"]
    assert "defect.py:9" in diags[0].location


def test_codelint_lock_and_pragma_suppression(tmp_path):
    src = SEEDED_DEFECT.replace(
        "_cache[key] = value          # unsynchronized!",
        "_cache[key] = value          # codelint: ok -- test only")
    p = tmp_path / "suppressed.py"
    p.write_text(src)
    assert codelint.lint_paths([str(p)]) == []


def test_codelint_global_rebind_and_class_attr(tmp_path):
    p = tmp_path / "more.py"
    p.write_text('''
_handle = None


class Shared:
    count = 0

    def bump(self):
        Shared.count += 1


def set_handle(x):
    global _handle
    _handle = x
''')
    got = set(error_codes(codelint.lint_paths([str(p)])))
    assert got == {"CL002", "CL003"}


def test_codelint_local_shadowing_not_flagged(tmp_path):
    p = tmp_path / "shadow.py"
    p.write_text('''
_cache = {}


def fine():
    _cache = {}          # a fresh local, not the module global
    _cache["x"] = 1
    return _cache
''')
    assert codelint.lint_paths([str(p)]) == []


def test_codelint_shipped_tree_is_clean():
    """Acceptance: zero error-severity findings on the shipped tree."""
    diags = codelint.lint_paths(
        [os.path.join(REPO, "jepsen_tpu")],
        package_root=os.path.join(REPO, "jepsen_tpu"))
    assert analysis.errors(diags) == [], \
        analysis.render_text(diags)


def test_threaded_reachability_ranks_modules():
    import glob
    files = glob.glob(os.path.join(REPO, "jepsen_tpu", "**", "*.py"),
                      recursive=True)
    reach = codelint.threaded_modules(files,
                                      os.path.join(REPO, "jepsen_tpu"))
    # thread spawners and their dependencies are in; leaf OS shims out
    assert "jepsen_tpu.interpreter" in reach
    assert "jepsen_tpu.history" in reach   # imported by checker path
    assert "jepsen_tpu.os.centos" not in reach


# ---------------------------------------------------------------------------
# tools/lint.py driver

def _run_lint(args):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--no-ruff"] + args,
        capture_output=True, text=True, timeout=120)


def test_lint_tool_zero_on_shipped_tree():
    r = _run_lint([os.path.join(REPO, "jepsen_tpu"),
                   os.path.join(REPO, "tools")])
    assert r.returncode == 0, r.stdout + r.stderr


def test_lint_tool_nonzero_on_seeded_defect(tmp_path):
    p = tmp_path / "defect.py"
    p.write_text(SEEDED_DEFECT)
    r = _run_lint([str(p)])
    assert r.returncode == 1
    assert "CL001" in r.stdout


def test_lint_tool_json_output(tmp_path):
    p = tmp_path / "defect.py"
    p.write_text(SEEDED_DEFECT)
    r = _run_lint(["--json", str(p)])
    report = json.loads(r.stdout)
    assert report["failed"] is True
    assert report["counts"]["error"] == 1


# ---------------------------------------------------------------------------
# CLI --lint dry run

def test_cli_lint_dry_run(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-m", "jepsen_tpu", "test", "--workload",
         "noop", "--no-ssh", "--lint"],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "plan lint" in r.stdout
    # a dry run creates no store directory
    assert not (tmp_path / "store").exists()
