"""Golden-history tests for the long-fork, causal, causal-reverse, and
adya workloads (reference tests/{long_fork,causal,causal_reverse,
adya}.clj)."""

import random


from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.generator.testing import perfect, simulate
from jepsen_tpu.tests import adya, causal, causal_reverse, long_fork


# -- long fork ---------------------------------------------------------------

def _read(txn, **kw):
    return {"type": "ok", "f": "read", "process": kw.get("process", 0),
            "value": txn, "time": kw.get("time", 0)}


def test_long_fork_detects_fork():
    hist = [
        {"type": "ok", "f": "write", "process": 0,
         "value": [["w", 0, 1]], "time": 0},
        {"type": "ok", "f": "write", "process": 1,
         "value": [["w", 1, 1]], "time": 1},
        _read([["r", 0, 1], ["r", 1, None]], process=2, time=2),
        _read([["r", 0, None], ["r", 1, 1]], process=3, time=3),
    ]
    res = long_fork.checker(2).check({}, hist)
    assert res["valid"] is False
    assert len(res["forks"]) == 1


def test_long_fork_valid_comparable_reads():
    hist = [
        _read([["r", 0, 1], ["r", 1, None]], process=0),
        _read([["r", 0, 1], ["r", 1, 1]], process=1),
        _read([["r", 0, None], ["r", 1, None]], process=2),
    ]
    res = long_fork.checker(2).check({}, hist)
    assert res["valid"] is True
    assert res["reads-count"] == 3
    assert res["early-read-count"] == 1
    assert res["late-read-count"] == 1


def test_long_fork_multiple_writes_unknown():
    hist = [
        {"type": "invoke", "f": "write", "process": 0,
         "value": [["w", 7, 1]], "time": 0},
        {"type": "invoke", "f": "write", "process": 1,
         "value": [["w", 7, 1]], "time": 1},
    ]
    res = long_fork.checker(2).check({}, hist)
    assert res["valid"] == "unknown"
    assert res["error"][0] == "multiple-writes"


def test_long_fork_distinct_values_unknown():
    hist = [
        _read([["r", 0, 1], ["r", 1, None]], process=0),
        _read([["r", 0, 2], ["r", 1, None]], process=1),
    ]
    res = long_fork.checker(2).check({}, hist)
    assert res["valid"] == "unknown"


def test_long_fork_generator_shape():
    random.seed(45100)
    test = {"nodes": ["n1", "n2"], "concurrency": 4}
    hist = simulate(test, gen.limit(40, long_fork.generator(2)), perfect)
    invokes = [o for o in hist if o["type"] == "invoke"]
    writes = [o for o in invokes if o["f"] == "write"]
    reads = [o for o in invokes if o["f"] == "read"]
    assert writes and reads
    # writes use unique fresh keys
    wkeys = [o["value"][0][1] for o in writes]
    assert len(set(wkeys)) == len(wkeys)
    # every read covers a full group of 2
    assert all(len({m[1] for m in o["value"]}) == 2 for o in reads)


# -- causal ------------------------------------------------------------------

def _c(f, value, pos, link, typ="ok"):
    return {"type": typ, "f": f, "value": value, "position": pos,
            "link": link, "process": 0, "time": pos}


def test_causal_valid_chain():
    hist = [
        _c("read-init", None, 1, "init"),
        _c("write", 1, 2, 1),
        _c("read", 1, 3, 2),
        _c("write", 2, 4, 3),
        _c("read", 2, 5, 4),
    ]
    res = causal.check(causal.causal_register()).check({}, hist)
    assert res["valid"] is True


def test_causal_broken_link():
    hist = [
        _c("read-init", None, 1, "init"),
        _c("write", 1, 2, 99),   # links to a position never seen
    ]
    res = causal.check(causal.causal_register()).check({}, hist)
    assert res["valid"] is False
    assert "Cannot link" in res["error"]


def test_causal_stale_read():
    hist = [
        _c("read-init", None, 1, "init"),
        _c("write", 1, 2, 1),
        _c("write", 2, 3, 2),
        _c("read", 1, 4, 3),     # stale: register is now 2
    ]
    res = causal.check(causal.causal_register()).check({}, hist)
    assert res["valid"] is False


def test_causal_bad_write_value():
    hist = [
        _c("read-init", None, 1, "init"),
        _c("write", 7, 2, 1),    # expected counter value 1
    ]
    res = causal.check(causal.causal_register()).check({}, hist)
    assert res["valid"] is False


# -- causal reverse ----------------------------------------------------------

def test_causal_reverse_detects_reversal():
    hist = [
        {"type": "invoke", "f": "write", "value": 0, "process": 0},
        {"type": "ok", "f": "write", "value": 0, "process": 0},
        # w1 invoked after w0 completed: w0 must be visible wherever w1 is
        {"type": "invoke", "f": "write", "value": 1, "process": 1},
        {"type": "ok", "f": "write", "value": 1, "process": 1},
        {"type": "invoke", "f": "read", "value": None, "process": 2},
        {"type": "ok", "f": "read", "value": [1], "process": 2},
    ]
    res = causal_reverse.checker().check({}, hist)
    assert res["valid"] is False
    assert res["errors"][0]["missing"] == [0]


def test_causal_reverse_valid():
    hist = [
        {"type": "invoke", "f": "write", "value": 0, "process": 0},
        {"type": "ok", "f": "write", "value": 0, "process": 0},
        {"type": "invoke", "f": "write", "value": 1, "process": 1},
        {"type": "ok", "f": "write", "value": 1, "process": 1},
        {"type": "invoke", "f": "read", "value": None, "process": 2},
        {"type": "ok", "f": "read", "value": [0, 1], "process": 2},
    ]
    assert causal_reverse.checker().check({}, hist)["valid"] is True


def test_causal_reverse_concurrent_writes_ok():
    # w0 and w1 overlap; a read may see either subset
    hist = [
        {"type": "invoke", "f": "write", "value": 0, "process": 0},
        {"type": "invoke", "f": "write", "value": 1, "process": 1},
        {"type": "ok", "f": "write", "value": 0, "process": 0},
        {"type": "ok", "f": "write", "value": 1, "process": 1},
        {"type": "invoke", "f": "read", "value": None, "process": 2},
        {"type": "ok", "f": "read", "value": [1], "process": 2},
    ]
    assert causal_reverse.checker().check({}, hist)["valid"] is True


# -- adya --------------------------------------------------------------------

def test_adya_g2_checker():
    T = independent.tuple_
    good = [
        {"type": "ok", "f": "insert", "value": T(0, [1, None])},
        {"type": "fail", "f": "insert", "value": T(0, [None, 2])},
        {"type": "ok", "f": "insert", "value": T(1, [3, None])},
    ]
    res = adya.g2_checker().check({}, good)
    assert res["valid"] is True
    assert res["key-count"] == 2

    bad = good + [{"type": "ok", "f": "insert", "value": T(0, [None, 9])}]
    res = adya.g2_checker().check({}, bad)
    assert res["valid"] is False
    assert 0 in res["illegal"]


def test_adya_generator_pairs():
    random.seed(45100)
    g = adya.g2_gen()
    test = {"nodes": ["n1"], "concurrency": 4}
    hist = simulate(test, gen.limit(12, g), perfect)
    invokes = [o for o in hist if o["type"] == "invoke"]
    by_key = {}
    ids = []
    for o in invokes:
        k, pair = o["value"][0], o["value"][1]
        by_key.setdefault(k, []).append(pair)
        ids.extend(x for x in pair if x is not None)
    # ids globally unique, exactly one of a/b per op, two ops per key
    assert len(set(ids)) == len(ids)
    assert all(sum(x is not None for x in p) == 1
               for ps in by_key.values() for p in ps)
    assert all(len(ps) <= 2 for ps in by_key.values())


def test_causal_reverse_generator_runs():
    """The workload generator must mix reads and writes throughout (reads
    are not one-shot) and run under simulation."""
    random.seed(45100)
    wl = causal_reverse.workload({"nodes": ["n1"], "per-key-limit": 40})
    test = {"nodes": ["n1"], "concurrency": 1}
    hist = simulate(test, gen.time_limit(2, wl["generator"]), perfect)
    invokes = [o for o in hist if o["type"] == "invoke"]
    assert len(invokes) > 10
    fs = [o["f"] for o in invokes]
    assert fs.count("read") >= 3 and fs.count("write") >= 3
    # reads keep appearing after the first few ops
    assert "read" in fs[len(fs) // 2:]
