"""Golden-history tests for the checker library.

Mirrors the coverage of reference
jepsen/test/jepsen/checker_test.clj:18-682 — hand-written histories in,
verdict maps out — plus the competition unknown-winner path
(checker.clj:199-202 semantics) that round 1 shipped untested.
"""

import threading
import time

import pytest

from jepsen_tpu import history as h
from jepsen_tpu import models
from jepsen_tpu.checker import checkers as ck
from jepsen_tpu.checker import core as cc

inv = h.invoke_op
ok = h.ok_op


def fail(process, f, value=None, **kw):
    return h.op("fail", process, f, value, **kw)


def info(process, f, value=None, **kw):
    return h.op("info", process, f, value, **kw)


def check(checker, hist, test=None, opts=None):
    return cc.check(checker, test or {}, hist, opts)


# ---------------------------------------------------------------------------
# unhandled-exceptions (checker_test.clj:17-42)

def test_unhandled_exceptions():
    r = check(ck.unhandled_exceptions(), [
        inv(0, "foo", 1),
        info(0, "foo", 1, exception="IllegalArgumentException"),
        inv(0, "foo", 1),
        info(0, "foo", 1, exception="IllegalArgumentException"),
        inv(0, "foo", 1),
        info(0, "foo", 1, exception="IllegalStateException"),
    ])
    assert r["valid"] is True
    assert [e["count"] for e in r["exceptions"]] == [2, 1]
    assert r["exceptions"][0]["class"] == "IllegalArgumentException"


def test_unhandled_exceptions_empty():
    r = check(ck.unhandled_exceptions(), [])
    assert r == {"valid": True}


# ---------------------------------------------------------------------------
# stats (checker_test.clj:44-67)

def test_stats():
    r = check(ck.stats(), [
        h.op("ok", 0, "foo"),
        h.op("fail", 0, "foo"),
        h.op("info", 0, "bar"),
        h.op("fail", 0, "bar"),
        h.op("fail", 0, "bar"),
    ])
    assert r["valid"] is False
    assert r["count"] == 5
    assert r["ok-count"] == 1
    assert r["fail-count"] == 3
    assert r["info-count"] == 1
    assert r["by-f"]["foo"]["valid"] is True
    assert r["by-f"]["foo"]["count"] == 2
    assert r["by-f"]["bar"]["valid"] is False
    assert r["by-f"]["bar"]["info-count"] == 1


def test_stats_ignores_invokes_and_nemesis():
    r = check(ck.stats(), [
        inv(0, "w", 1),
        h.op("info", "nemesis", "start"),
        ok(0, "w", 1),
    ])
    assert r["valid"] is True
    assert r["count"] == 1


# ---------------------------------------------------------------------------
# queue (checker_test.clj:69-88)

def test_queue_empty():
    assert check(ck.queue(None), [])["valid"] is True


def test_queue_possible_enqueue_no_dequeue():
    r = check(ck.queue(models.unordered_queue()), [inv(1, "enqueue", 1)])
    assert r["valid"] is True


def test_queue_definite_enqueue_no_dequeue():
    r = check(ck.queue(models.unordered_queue()), [ok(1, "enqueue", 1)])
    assert r["valid"] is True


def test_queue_concurrent_enqueue_dequeue():
    r = check(ck.queue(models.unordered_queue()), [
        inv(2, "dequeue"),
        inv(1, "enqueue", 1),
        ok(2, "dequeue", 1),
    ])
    assert r["valid"] is True


def test_queue_dequeue_without_enqueue():
    r = check(ck.queue(models.unordered_queue()), [ok(1, "dequeue", 1)])
    assert r["valid"] is False


# ---------------------------------------------------------------------------
# total-queue (checker_test.clj:90-143)

def test_total_queue_sane():
    r = check(ck.total_queue(), [
        inv(1, "enqueue", 1),
        inv(2, "enqueue", 2),
        ok(2, "enqueue", 2),
        inv(3, "dequeue", 1),
        ok(3, "dequeue", 1),
        inv(3, "dequeue", 2),
        ok(3, "dequeue", 2),
    ])
    assert r["valid"] is True
    assert r["attempt-count"] == 2
    assert r["acknowledged-count"] == 1
    assert r["ok-count"] == 2
    assert r["recovered-count"] == 1
    assert r["recovered"] == {1: 1}
    assert r["lost-count"] == 0


def test_total_queue_pathological():
    r = check(ck.total_queue(), [
        inv(1, "enqueue", "hung"),
        inv(2, "enqueue", "enqueued"),
        ok(2, "enqueue", "enqueued"),
        inv(3, "enqueue", "dup"),
        ok(3, "enqueue", "dup"),
        inv(4, "dequeue"),      # hangs
        inv(5, "dequeue"),
        ok(5, "dequeue", "wtf"),
        inv(6, "dequeue"),
        ok(6, "dequeue", "dup"),
        inv(7, "dequeue"),
        ok(7, "dequeue", "dup"),
    ])
    assert r["valid"] is False
    assert r["lost"] == {"enqueued": 1}
    assert r["unexpected"] == {"wtf": 1}
    assert r["duplicated"] == {"dup": 1}
    assert r["attempt-count"] == 3
    assert r["acknowledged-count"] == 2
    assert r["ok-count"] == 1


def test_expand_queue_drain_ops():
    hist = [
        inv(1, "drain"),
        ok(1, "drain", [1, 2]),
    ]
    out = ck.expand_queue_drain_ops(hist)
    assert [(o["type"], o["f"], o.get("value")) for o in out] == [
        ("invoke", "dequeue", None), ("ok", "dequeue", 1),
        ("invoke", "dequeue", None), ("ok", "dequeue", 2)]


# ---------------------------------------------------------------------------
# counter (checker_test.clj:145-222)

def test_counter_empty():
    assert check(ck.counter(), []) == {"valid": True, "reads": [],
                                       "errors": []}


def test_counter_initial_read():
    r = check(ck.counter(), [inv(0, "read"), ok(0, "read", 0)])
    assert r == {"valid": True, "reads": [[0, 0, 0]], "errors": []}


def test_counter_ignores_failed_ops():
    r = check(ck.counter(), [
        inv(0, "add", 1),
        fail(0, "add", 1),
        inv(0, "read"),
        ok(0, "read", 0),
    ])
    assert r == {"valid": True, "reads": [[0, 0, 0]], "errors": []}


def test_counter_initial_invalid_read():
    r = check(ck.counter(), [inv(0, "read"), ok(0, "read", 1)])
    assert r == {"valid": False, "reads": [[0, 1, 0]],
                 "errors": [[0, 1, 0]]}


def test_counter_interleaved():
    r = check(ck.counter(), [
        inv(0, "read"),
        inv(1, "add", 1),
        inv(2, "read"),
        inv(3, "add", 2),
        inv(4, "read"),
        inv(5, "add", 4),
        inv(6, "read"),
        inv(7, "add", 8),
        inv(8, "read"),
        ok(0, "read", 6),
        ok(1, "add", 1),
        ok(2, "read", 0),
        ok(3, "add", 2),
        ok(4, "read", 3),
        ok(5, "add", 4),
        ok(6, "read", 100),
        ok(7, "add", 8),
        ok(8, "read", 15),
    ])
    assert r["valid"] is False
    assert r["reads"] == [[0, 6, 15], [0, 0, 15], [0, 3, 15],
                          [0, 100, 15], [0, 15, 15]]
    assert r["errors"] == [[0, 100, 15]]


def test_counter_rolling():
    r = check(ck.counter(), [
        inv(0, "read"),
        inv(1, "add", 1),
        ok(0, "read", 0),
        inv(0, "read"),
        ok(1, "add", 1),
        inv(1, "add", 2),
        ok(0, "read", 3),
        inv(0, "read"),
        ok(1, "add", 2),
        ok(0, "read", 5),
    ])
    assert r["valid"] is False
    assert r["reads"] == [[0, 0, 1], [0, 3, 3], [1, 5, 3]]
    assert r["errors"] == [[1, 5, 3]]


def test_counter_negative_adds_no_crash():
    # the reference returns verdicts, never raises, on odd histories
    r = check(ck.counter(), [
        inv(0, "add", -3),
        ok(0, "add", -3),
        inv(0, "read"),
        ok(0, "read", -3),
    ])
    assert r["valid"] is True


# ---------------------------------------------------------------------------
# set (checker.clj:240-291)

def test_set_never_read():
    r = check(ck.set_checker(), [inv(0, "add", 1), ok(0, "add", 1)])
    assert r["valid"] == "unknown"


def test_set_lost_and_unexpected():
    r = check(ck.set_checker(), [
        inv(0, "add", 0),
        ok(0, "add", 0),
        inv(0, "add", 1),
        ok(0, "add", 1),
        inv(1, "add", 2),      # attempted, never acked
        info(1, "add", 2),
        inv(0, "read"),
        ok(0, "read", [0, 2, 99]),   # 1 lost, 99 unexpected, 2 recovered
    ])
    assert r["valid"] is False
    assert r["lost"] == [1]
    assert r["unexpected"] == [99]
    assert r["recovered"] == [2]
    assert r["attempt-count"] == 3
    assert r["acknowledged-count"] == 2


def test_set_valid():
    r = check(ck.set_checker(), [
        inv(0, "add", 1),
        ok(0, "add", 1),
        inv(0, "read"),
        ok(0, "read", [1]),
    ])
    assert r["valid"] is True


# ---------------------------------------------------------------------------
# set-full (checker.clj:294-592; checker_test.clj set-full-test)

def _t(o, t):
    o = dict(o)
    o["time"] = t
    return o


def test_set_full_stable():
    r = check(ck.set_full(), [
        _t(inv(0, "add", 0), 0),
        _t(ok(0, "add", 0), 1),
        _t(inv(1, "read"), 2),
        _t(ok(1, "read", [0]), 3),
    ])
    assert r["valid"] is True
    assert r["stable-count"] == 1
    assert r["lost-count"] == 0


def test_set_full_lost():
    r = check(ck.set_full(), [
        _t(inv(0, "add", 0), 0),
        _t(ok(0, "add", 0), 1),
        _t(inv(1, "read"), 2),
        _t(ok(1, "read", [0]), 3),
        _t(inv(1, "read"), 4),
        _t(ok(1, "read", []), 5),    # later read loses it
    ])
    assert r["valid"] is False
    assert r["lost"] == [0]


def test_set_full_never_read_unknown():
    r = check(ck.set_full(), [
        _t(inv(0, "add", 0), 0),
        _t(ok(0, "add", 0), 1),
    ])
    assert r["valid"] == "unknown"


def test_set_full_duplicate_invalid():
    r = check(ck.set_full(), [
        _t(inv(0, "add", 0), 0),
        _t(ok(0, "add", 0), 1),
        _t(inv(1, "read"), 2),
        _t(ok(1, "read", [0, 0]), 3),
    ])
    assert r["valid"] is False
    assert r["duplicated"] == {0: 2}


def test_set_full_linearizable_stale():
    # element visible only *after* an absent read that begins after the
    # add completed -> stale under linearizable mode
    ms = 1_000_000  # history times are nanoseconds; latencies are in ms
    hist = [
        _t(inv(0, "add", 0), 0 * ms),
        _t(ok(0, "add", 0), 10 * ms),
        _t(inv(1, "read"), 20 * ms),
        _t(ok(1, "read", []), 30 * ms),      # absent after ack: stale
        _t(inv(1, "read"), 40 * ms),
        _t(ok(1, "read", [0]), 50 * ms),
    ]
    r = check(ck.set_full({"linearizable?": True}), hist)
    assert r["valid"] is False
    assert r["stale"] == [0]
    r2 = check(ck.set_full(), hist)
    assert r2["valid"] is True   # eventually-consistent mode tolerates it


# ---------------------------------------------------------------------------
# unique-ids (checker.clj:689-734)

def test_unique_ids_ok():
    r = check(ck.unique_ids(), [
        inv(0, "generate"),
        ok(0, "generate", 0),
        inv(0, "generate"),
        ok(0, "generate", 1),
    ])
    assert r["valid"] is True
    assert r["attempted-count"] == 2
    assert r["acknowledged-count"] == 2
    assert r["range"] == [0, 1]


def test_unique_ids_dup():
    r = check(ck.unique_ids(), [
        inv(0, "generate"),
        ok(0, "generate", 0),
        inv(0, "generate"),
        ok(0, "generate", 0),
    ])
    assert r["valid"] is False
    assert r["duplicated"] == {0: 2}


# ---------------------------------------------------------------------------
# log-file-pattern (checker.clj:839-881)

def test_log_file_pattern(tmp_path, monkeypatch):
    from jepsen_tpu import store
    monkeypatch.setattr(store, "base_dir", str(tmp_path))
    ts = "20260729T000000.000000+0000"
    test = {"name": "lfp", "start-time": ts, "nodes": ["n1", "n2"]}
    node_dir = tmp_path / "lfp" / ts / "n1"
    node_dir.mkdir(parents=True)
    (node_dir / "db.log").write_text("ok line\npanic: boom\nok line\n")
    r = check(ck.log_file_pattern(r"panic", "db.log"), [], test=test)
    assert r["valid"] is False
    assert r["count"] == 1
    assert r["matches"] == [{"node": "n1", "line": "panic: boom"}]


def test_log_file_pattern_no_store():
    r = check(ck.log_file_pattern(r"panic", "db.log"), [],
              test={"nodes": ["n1"]})
    assert r["valid"] == "unknown"


# ---------------------------------------------------------------------------
# compose / check-safe / merge-valid / concurrency-limit
# (checker_test.clj:224-229)

def test_compose():
    r = check(cc.compose({"a": cc.unbridled_optimism(),
                          "b": cc.unbridled_optimism()}), [])
    assert r["valid"] is True
    assert r["a"]["valid"] is True
    assert r["b"]["valid"] is True


def test_compose_merges_worst():
    class Bad(cc.Checker):
        def check(self, test, hist, opts=None):
            return {"valid": False}

    r = check(cc.compose({"good": cc.noop(), "bad": Bad()}), [])
    assert r["valid"] is False


def test_check_safe_catches():
    class Boom(cc.Checker):
        def check(self, test, hist, opts=None):
            raise RuntimeError("boom")

    r = cc.check_safe(Boom(), {}, [])
    assert r["valid"] == "unknown"
    assert "boom" in r["error"]


def test_merge_valid():
    assert cc.merge_valid([True, True]) is True
    assert cc.merge_valid([True, "unknown"]) == "unknown"
    assert cc.merge_valid([False, "unknown", True]) is False
    assert cc.merge_valid([]) is True


def test_concurrency_limit():
    active = []
    peak = []
    lock = threading.Lock()

    class Slow(cc.Checker):
        def check(self, test, hist, opts=None):
            with lock:
                active.append(1)
                peak.append(len(active))
            time.sleep(0.02)
            with lock:
                active.pop()
            return {"valid": True}

    limited = cc.concurrency_limit(2, Slow(), key="test-limit")
    threads = [threading.Thread(target=limited.check, args=({}, []))
               for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert max(peak) <= 2


# ---------------------------------------------------------------------------
# linearizable gate (checker.clj:185-216)

GOOD_CAS = [
    inv(0, "write", 1),
    ok(0, "write", 1),
    inv(1, "read"),
    ok(1, "read", 1),
    inv(0, "cas", [1, 2]),
    ok(0, "cas", [1, 2]),
    inv(1, "read"),
    ok(1, "read", 2),
]

BAD_CAS = [
    inv(0, "write", 1),
    ok(0, "write", 1),
    inv(1, "read"),
    ok(1, "read", 7),     # never written
]


@pytest.mark.parametrize("algo", ["wgl", "linear", "jax-wgl", "competition"])
def test_linearizable_verdicts(algo):
    c = ck.linearizable({"model": "cas-register", "algorithm": algo})
    assert check(c, GOOD_CAS)["valid"] is True
    assert check(c, BAD_CAS)["valid"] is False


def test_linearizable_requires_model():
    with pytest.raises(Exception):
        ck.linearizable({"model": None})


def test_linearizable_ignores_nemesis_ops():
    hist = [h.op("info", "nemesis", "start")] + GOOD_CAS + \
           [h.op("info", "nemesis", "stop")]
    c = ck.linearizable({"model": "cas-register", "algorithm": "wgl"})
    assert check(c, hist)["valid"] is True


def test_competition_unknown_winner_defers_to_loser(monkeypatch):
    """If the first engine to finish returns unknown, competition must wait
    for another and take its definite verdict (checker.clj:199-202)."""
    from jepsen_tpu.checker import jax_wgl, linear, wgl

    def fast_unknown(spec, e, init_state, **kw):
        return {"valid": "unknown", "error": "budget"}

    real = wgl.check_encoded

    def slow_definite(spec, e, init_state, **kw):
        kw.pop("max_configs", None)
        time.sleep(0.05)
        return real(spec, e, init_state)

    monkeypatch.setattr(jax_wgl, "check_encoded", fast_unknown)
    monkeypatch.setattr(linear, "check_encoded", fast_unknown)
    monkeypatch.setattr(wgl, "check_encoded", slow_definite)
    c = ck.linearizable({"model": "cas-register"})
    r = check(c, GOOD_CAS)
    assert r["valid"] is True
    assert r["engine"] == "wgl"


def test_competition_all_unknown(monkeypatch):
    from jepsen_tpu.checker import jax_wgl, linear, wgl

    def unknown(spec, e, init_state, **kw):
        return {"valid": "unknown", "error": "budget"}

    monkeypatch.setattr(jax_wgl, "check_encoded", unknown)
    monkeypatch.setattr(linear, "check_encoded", unknown)
    monkeypatch.setattr(wgl, "check_encoded", unknown)
    c = ck.linearizable({"model": "cas-register"})
    r = check(c, GOOD_CAS)
    assert r["valid"] == "unknown"


def test_linearizable_truncates_witness_fields(monkeypatch):
    """At most 10 paths / 10 configs survive (checker.clj:213-216)."""
    from jepsen_tpu.checker import wgl

    def fat(spec, e, init_state, **kw):
        return {"valid": False,
                "final_paths": [[{"op": i}] for i in range(50)],
                "configs": [{"model": i} for i in range(50)]}

    monkeypatch.setattr(wgl, "check_encoded", fat)
    c = ck.linearizable({"model": "cas-register", "algorithm": "wgl"})
    r = check(c, BAD_CAS)
    assert len(r["final_paths"]) == 10
    assert len(r["configs"]) == 10


def test_invalid_check_carries_knossos_witness_fields():
    """An invalid verdict from either SEARCH engine ships the knossos
    artifact set: op, final_paths (step-by-step (op, model) sequence),
    previous_ok, configs with pending candidates (checker.clj:206-216;
    VERDICT r2 missing #2). BAD_CAS is decided by the state-abstraction
    fast path on the device engine, so use a history whose bad read
    value IS written elsewhere (timing, not reachability, is wrong)."""
    bad = [
        inv(0, "write", 1), ok(0, "write", 1),
        inv(1, "read"), ok(1, "read", 2),     # before write 2 begins
        inv(0, "write", 2), ok(0, "write", 2),
    ]
    for algo in ("wgl", "jax-wgl", "linear"):
        c = ck.linearizable({"model": "cas-register", "algorithm": algo})
        r = check(c, bad)
        assert r["valid"] is False
        assert r["op"]["f"] is not None
        assert r["final_paths"], algo
        path = r["final_paths"][0]
        assert all("op" in s and "model" in s for s in path)
        # states decode into the readable model face
        assert all(isinstance(s["model"], dict) for s in path)
        assert r["configs"] and "pending" in r["configs"][0]
        assert r["configs"][0]["model"] is not None
