"""Benchmark: the BASELINE.json config ladder for the device WGL engine.

Rungs (BASELINE.md north-star table):
  0. max single-key history length decidable in 60 s (primary metric),
     measured to the engine's limit by exponential growth + bisection,
     per model, including a raw-search FIFO row (round-4 rework)
  1. single ~200-op cas-register histories     (CPU-parity baseline)
  2. 32-key batched per-key checks, one chip   (jepsen.independent style)
  2b. 256-key batch -- the throughput headline since round 3
  2c. 1024-key batch + the keys-vs-throughput curve (headline is the
      best of 2b/2c)
  3. mutex, high contention
  4/4b. FIFO queue, info-free (aspect fast path)
  4c. 10k-op FIFO with info dequeues (exact aspect, round-3 extension)
  4d. 2k-op info FIFO through the RAW search engine (witness-order hint)
  5. 10k-op / 64-process cas-register with many info ops
     (the stretch goal: decided on device where the CPU oracle gives up)
  6. linear engine home turf: 50k-op 2-process crash-free history where
     the CPU event sweep beats the device search (the racer is real)
  7. streaming-monitor detection latency on an injected violation
  8. fleet compile-ledger reuse: the same 2x2 matrix run twice in two
     SEPARATE scheduler processes; the warm process must report
     persistent-ledger hits > 0, with cold-vs-warm wall clock recorded
  9. search-plan reduction: the same quiescent 4-key register history
     checked with the searchplan analyzer on and off; the detail
     records segment count, config-count estimate vs actual, wall
     clock for both paths, and the planner's own cost fraction
  11. obs overhead: the same fixed-op run with the tracer + crash-safe
      telemetry journals ON vs obs OFF entirely; the fleet telemetry
      plane must cost < 5% of clean-run wall clock
  12. introspection overhead: the same fixed device WGL search with
      the search-progress telemetry (per-dispatch progress-tensor
      device reads, heartbeats, padding accounting, journal flushes)
      ON vs obs OFF entirely — interleaved OFF/ON pairs, min-of-N
      quiet-floor estimator (rung 11's methodology); must stay < 5%,
      with explored-configs and device duty cycle in the detail so
      the optimization arc restarts from a measured baseline

The baseline is the sequential CPU WGL oracle (our knossos stand-in,
checker/wgl.py) with a 60 s / config-capped budget per history.

Prints TWO JSON lines: the full detail blob first, then a SHORT
headline-only line {"metric", "value", "unit", "vs_baseline",
"headline_rung"} LAST -- the driver's tail capture must always catch a
parseable headline (BENCH_r04's detail-first single line pushed the
headline out of the captured tail, VERDICT r4 weak #1). Since round 3
the headline value is the 256/1024-key batch rate (rounds 1-2 reported
the 32-key rung 2 rate, still present in the detail for a
like-for-like trend; vs_baseline divides by the single-thread CPU
oracle rate measured on the 32-key subset). The batch rungs are timed
as median-of-3 (single-shot points were stall-poisoned by TPU-tunnel
hiccups: BENCH_r04's 256-key point read 2,622 ops/s against a stable
~8k, VERDICT r4 weak #2); per-run times ship in the detail.
"""

import json
import random
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0])

ORACLE_BUDGET_S = 60.0


def timed3(fn):
    """Median-of-3 timed runs. Returns (median_s, sorted runs, last
    result). The TPU tunnel stalls for whole minutes at a time
    (observed single dispatches of 117-1029 s); a median over three
    warm runs keeps one stall from poisoning a reported rate."""
    runs = []
    res = None
    for _ in range(3):
        t0 = time.monotonic()
        res = fn()
        runs.append(round(time.monotonic() - t0, 3))
    return sorted(runs)[1], sorted(runs), res


def _oracle_worker(spec_name, hist, q):
    import sys as _s
    _s.path.insert(0, __file__.rsplit("/", 1)[0])
    from jepsen_tpu.checker import wgl
    from jepsen_tpu.models import model_spec
    spec = model_spec(spec_name)
    e, st = spec.encode(hist)
    t0 = time.monotonic()
    r = wgl.check_encoded(spec, e, st, max_configs=50_000_000)
    q.put({"valid": r["valid"], "s": time.monotonic() - t0})


class OracleRace:
    """CPU oracle in a killable subprocess (a timed-out thread would keep
    burning CPU under the device benches)."""

    def __init__(self, spec_name, hist):
        import multiprocessing as mp
        self.ctx = mp.get_context("spawn")
        self.q = self.ctx.Queue()
        self.p = self.ctx.Process(target=_oracle_worker,
                                  args=(spec_name, hist, self.q),
                                  daemon=True)
        self.t0 = time.monotonic()
        self.p.start()

    def result(self, budget_s=ORACLE_BUDGET_S):
        left = max(0.0, budget_s - (time.monotonic() - self.t0))
        self.p.join(timeout=left)
        out = {"valid": "unknown", "error": "timeout",
               "s": min(budget_s, time.monotonic() - self.t0)}
        try:
            # a process that exited cleanly has a result, but it may still
            # be in the queue's pipe buffer right after join(): block
            # briefly rather than misreport a near-deadline finish as a
            # timeout
            if self.p.exitcode == 0:
                got = self.q.get(timeout=5)
            else:
                got = self.q.get_nowait()
            out.update(got)
            out.pop("error", None)
        except Exception:  # noqa: BLE001 - empty queue = still running
            pass
        if self.p.is_alive():
            self.p.terminate()
        return out


def _monitor_rung(n_ops=512, violate_at=256, chunk=64):
    """Streaming-monitor detection metrics (jepsen_tpu.monitor): feed a
    synthetic cas-register stream with a violation injected half way
    (a read of a never-written value -- definitively invalid) through
    a standalone Monitor on the device engine, and report

      time_to_first_verdict_s  wall from monitor start to its first
                               definite chunk verdict (compile + first
                               search; the cold-start cost)
      detection_latency_s      wall from the violating op landing to
                               the violation being proven
      abort_latency_s          wall from the violating op landing to
                               the abort latch actually flipping

    Self-contained and never fatal: a monitor regression must show up
    as numbers (or an error field), not break the throughput bench."""
    try:
        from jepsen_tpu import monitor as jmon
        from jepsen_tpu import robust
        from jepsen_tpu.models import model_spec
        spec = model_spec("cas-register")
        latch = robust.ChainedLatch()
        mon = jmon.Monitor(spec, latch, chunk=chunk,
                           engine="jax-wgl").start()
        t_violation = None
        val = 0
        for i in range(n_ops):
            if i == violate_at:
                ops = [{"type": "invoke", "process": 0, "f": "read",
                        "value": None},
                       {"type": "ok", "process": 0, "f": "read",
                        "value": 10**6}]
            elif i % 2 == 0:
                val = i + 1
                ops = [{"type": "invoke", "process": 0, "f": "write",
                        "value": val},
                       {"type": "ok", "process": 0, "f": "write",
                        "value": val}]
            else:
                ops = [{"type": "invoke", "process": 0, "f": "read",
                        "value": None},
                       {"type": "ok", "process": 0, "f": "read",
                        "value": val}]
            for op in ops:
                mon.offer(op)
            if i == violate_at:
                t_violation = time.monotonic()
            if latch.is_set():
                break
        detected = latch.wait(120)
        t_abort = time.monotonic()
        mon.stop()
        s = mon.summary()
        return {
            "detected": bool(detected),
            "verdict": s.get("verdict"),
            "chunk": chunk,
            "ops_consumed": s.get("ops_consumed"),
            "checks": s.get("checks"),
            "time_to_first_verdict_s": s.get("time_to_first_verdict_s"),
            "detection_latency_s": s.get("detection_latency_s"),
            "abort_latency_s": (round(t_abort - t_violation, 4)
                                if detected and t_violation is not None
                                else None),
            "detected_at_index": s.get("detected_at_index"),
        }
    except Exception as exc:  # noqa: BLE001 - numbers, not crashes
        return {"error": repr(exc)}


def _stream_monitor_rung(n_streams=100, rounds=24, chunk=8,
                         violate_every=10):
    """Device-resident frontier monitoring at fleet width (rung 16,
    checker/streamlin + monitor/wgl_stream): drive ``n_streams``
    concurrent monitored cas-register streams, every ``violate_every``-th
    one carrying an injected stale read at the half-way round, in two
    modes --

      off  the pre-streamlin behavior: per-chunk FLAT re-search of the
           whole materialized prefix (mengine.check_prefix, jax-wgl)
      on   StreamCheck frontiers with the service Coalescer up, so
           strangers' frontier folds share padded (model, bucket)
           device batches

    and report sustained monitored-ops/s per mode, detection latency
    p50/p99 across the violating streams (violating op offered ->
    check proves False), the device duty cycle from the
    ``wgl.device_busy_s`` counter over each mode's wall (the PR 13
    metrics plane), per-chunk fold cost from the stream counters (the
    observable O(window) claim), and the coalescer's batch/segment/
    owners evidence (acceptance: batches > 0 with owners >= 2).
    Self-contained and never fatal."""
    import threading as _threading

    try:
        from jepsen_tpu import obs
        from jepsen_tpu.fleet import service
        from jepsen_tpu.models import model_spec
        from jepsen_tpu.monitor import engine as _mengine
        from jepsen_tpu.monitor.stream import StreamEncoder
        from jepsen_tpu.monitor.wgl_stream import StreamCheck

        spec = model_spec("cas-register")

        def reg_busy():
            reg = obs.registry()
            if reg is None:
                return 0.0
            return sum(v for k, v in
                       reg.snapshot()["counters"].items()
                       if k.startswith("wgl.device_busy_s"))

        def stream_ops(s, bad_round):
            ops, val = [], None
            for j in range(rounds):
                val = j + 1
                ops.append(({"type": "invoke", "process": 0,
                             "f": "write", "value": val}, None))
                ops.append(({"type": "ok", "process": 0,
                             "f": "write", "value": val}, None))
                rv = 10**6 if j == bad_round else val
                ops.append(({"type": "invoke", "process": 0,
                             "f": "read", "value": None}, None))
                ops.append(({"type": "ok", "process": 0,
                             "f": "read", "value": rv},
                            "violate" if j == bad_round else None))
            return ops

        def drive(mode):
            done = [0] * n_streams
            detect = {}
            streams_sc = []
            lock = _threading.Lock()

            def one(s):
                bad = rounds // 2 if s % violate_every == 0 else None
                if mode == "on":
                    sc = StreamCheck(spec, owner=f"bench-{s}")
                    with lock:
                        streams_sc.append(sc)
                else:
                    sc = StreamEncoder(spec)
                t_bad = None
                n = 0
                for i, (op, mark) in enumerate(stream_ops(s, bad)):
                    offered = sc.offer(op, i)
                    done[s] += 1
                    if mark == "violate":
                        t_bad = time.monotonic()
                    if offered:
                        n += 1
                        if n % chunk == 0 or mark == "violate":
                            if mode == "on":
                                r = sc.check()
                            else:
                                e, st = sc.materialize()
                                r = _mengine.check_prefix(
                                    spec, e, st, engine="jax-wgl")
                            if r["valid"] is False:
                                if t_bad is not None:
                                    with lock:
                                        detect[s] = (time.monotonic()
                                                     - t_bad)
                                return

            busy0 = reg_busy()
            t0 = time.monotonic()
            ths = [_threading.Thread(target=one, args=(s,))
                   for s in range(n_streams)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            wall = time.monotonic() - t0
            lat = sorted(detect.values())
            out = {
                "wall_s": round(wall, 2),
                "ops": sum(done),
                "ops_per_s": round(sum(done) / wall, 1) if wall else None,
                "streams": n_streams,
                "violating": n_streams // violate_every
                + (1 if n_streams % violate_every else 0),
                "detected": len(lat),
                "detect_p50_ms": round(lat[len(lat) // 2] * 1e3, 1)
                if lat else None,
                "detect_p99_ms": round(
                    lat[min(len(lat) - 1,
                            int(len(lat) * 0.99))] * 1e3, 1)
                if lat else None,
                "device_busy_s": round(reg_busy() - busy0, 3),
                "duty_cycle": round((reg_busy() - busy0) / wall, 4)
                if wall else None,
            }
            if mode == "on":
                folds = sum(sc.seal_folds + sc.probe_folds
                            for sc in streams_sc)
                cells = sum(sc.fold_cells for sc in streams_sc)
                out.update({
                    "folds": folds,
                    "cells_per_fold": round(cells / folds, 1)
                    if folds else None,
                    "coalesced_folds": sum(sc.coalesced_folds
                                           for sc in streams_sc),
                    "solo_folds": sum(sc.solo_folds
                                      for sc in streams_sc),
                    "flat_fallbacks": sum(sc.flat_checks
                                          for sc in streams_sc),
                    "frontier_peak": max((sc.frontier_peak
                                          for sc in streams_sc),
                                         default=None),
                    # widest batch any fold rode: each stream is its
                    # own owner with one in-flight fold, so a batch of
                    # K members is K distinct owners sharing a dispatch
                    "batch_peak": max((sc.batch_peak
                                       for sc in streams_sc),
                                      default=1),
                    "device_fold_s": round(sum(sc.device_s
                                               for sc in streams_sc),
                                           3),
                })
            return out

        # OFF first (no coalescer), then ON with the batcher up
        service.configure_coalesce(enabled=False)
        off = drive("off")
        service.configure_coalesce(enabled=True, window_ms=25)
        try:
            on = drive("on")
            st = service.coalescer().stats()
            on["batches"] = st["batches"]
            on["segments"] = st["segments"]
            reg = obs.registry()
            owners_max = None
            if reg is not None:
                h = reg.snapshot().get("histograms", {}).get(
                    "service.coalesce.owners")
                if h:
                    owners_max = h.get("max")
            # registry histogram when a metrics plane is up; the
            # stream-side batch_peak is the registry-free evidence
            # (each stream = one owner with one in-flight fold)
            on["owners_max"] = owners_max or on.get("batch_peak")
        finally:
            service.configure_coalesce(enabled=False)
        return {
            "chunk": chunk, "rounds": rounds,
            "off": off, "on": on,
            "speedup": round(on["ops_per_s"] / off["ops_per_s"], 2)
            if off.get("ops_per_s") and on.get("ops_per_s") else None,
            "goal_met": bool(
                on.get("detected") == on.get("violating")
                and off.get("detected") == off.get("violating")
                and (on.get("batches") or 0) > 0
                and (on.get("owners_max") or 0) >= 2),
        }
    except Exception as exc:  # noqa: BLE001 - numbers, not crashes
        return {"error": repr(exc)[:300]}


def _fleet_reuse_rung(time_limit_s=3, budget_s=600):
    """Cross-PROCESS compile reuse (jepsen_tpu.fleet.ledger): run the
    SAME 2x2 register matrix twice in two separate scheduler
    processes sharing one store, and report

      cold / warm           per-process wall clock, exit code, and the
                            campaign report's compile-cache delta
      cross_process_reuse   True iff the second process reported
                            ledger hits > 0 (shapes the first process
                            compiled counted as hits, not re-misses)

    The subprocesses are pinned to CPU: the bench process holds the
    accelerator, and the ledger's claim is platform-independent.
    Self-contained and never fatal: a regression must show up as
    numbers (or an error field), not break the throughput bench."""
    import os
    import subprocess
    import tempfile
    try:
        # NB not __file__.rsplit("/", 1): invoked as `python bench.py`
        # __file__ is relative and has no slash to split on, and the
        # subprocess (unlike this process) can't lean on cwd
        repo = os.path.dirname(os.path.abspath(__file__))
        workdir = tempfile.mkdtemp(prefix="jepsen-fleet-reuse-")
        env = {**os.environ, "PYTHONPATH": repo, "JAX_PLATFORMS": "cpu"}
        out = {"matrix": "workload=register x seeds=2",
               "time_limit_s": time_limit_s}
        for phase in ("cold", "warm"):
            t0 = time.monotonic()
            p = subprocess.run(
                [sys.executable, "-m", "jepsen_tpu", "campaign",
                 "--no-ssh", "--time-limit", str(time_limit_s),
                 "--axis", "workload=register", "--seeds", "2",
                 "--parallel", "2", "--campaign-id", f"reuse-{phase}"],
                cwd=workdir, capture_output=True, text=True,
                timeout=budget_s, env=env)
            wall = round(time.monotonic() - t0, 1)
            rep_path = os.path.join(workdir, "store", "campaigns",
                                    f"reuse-{phase}", "report.json")
            with open(rep_path) as f:
                rep = json.load(f)
            cc = rep.get("compile_cache") or {}
            out[phase] = {"wall_s": wall, "exit": p.returncode,
                          "hits": cc.get("hits"),
                          "misses": cc.get("misses"),
                          "ledger": cc.get("ledger")}
        out["cross_process_reuse"] = bool(
            (out["warm"].get("hits") or 0) > 0)
        out["warm_speedup"] = round(
            out["cold"]["wall_s"] / out["warm"]["wall_s"], 2) \
            if out["warm"]["wall_s"] else None
        return out
    except Exception as exc:  # noqa: BLE001 - numbers, not crashes
        return {"error": repr(exc)[:300]}


def _fleet_survival_rung(time_limit_s=2, budget_s=900):
    """Fleet survivability (jepsen_tpu.fleet sync/chaos): the same
    2-seed register matrix dispatched to 2 loopback workers with an
    ISOLATED worker store (artifact sync on), three ways:

      clean        no faults: baseline fleet wall clock
      chaos        --chaos-profile soak:7 (exit-255s, a hang, a
                   kill -9, a partial download, torn ledger tail):
                   wall clock + lease/steal/sync counts -- the price
                   of surviving, and proof every recovery path ran
      warm         the clean matrix again in a FRESH process sharing
                   the same store: with the persistent jax
                   compilation cache enabled, the restart should stop
                   paying the XLA compiles the first run did

    chaos_overhead_x is chaos wall / clean wall; warm reports the
    ledger's cold/warm wall split and the jax cache population.
    Self-contained and never fatal: a survivability regression must
    show up as numbers (or an error field), not break the bench."""
    import os
    import subprocess
    import tempfile
    try:
        repo = os.path.dirname(os.path.abspath(__file__))
        workdir = tempfile.mkdtemp(prefix="jepsen-fleet-survival-")
        env = {**os.environ, "PYTHONPATH": repo, "JAX_PLATFORMS": "cpu"}
        out = {"matrix": "workload=register x seeds=2",
               "time_limit_s": time_limit_s}
        # NB the warm phase REUSES the clean phase's worker store: the
        # persistent jax compilation cache + compile ledger live
        # there, and surviving a process restart is their whole claim
        wstores = {"clean": "wstore-clean", "chaos": "wstore-chaos",
                   "warm": "wstore-clean"}
        for phase, extra in (("clean", []),
                             ("chaos", ["--chaos-profile", "soak:7"]),
                             ("warm", [])):
            t0 = time.monotonic()
            p = subprocess.run(
                [sys.executable, "-m", "jepsen_tpu", "campaign",
                 "--no-ssh", "--time-limit", str(time_limit_s),
                 "--axis", "workload=register", "--seeds", "2",
                 "--parallel", "2", "--workers", "local,local",
                 "--lease", "300", "--max-leases", "5",
                 "--sync-timeout", "60",
                 "--worker-store",
                 os.path.join(workdir, wstores[phase]),
                 "--campaign-id", f"survival-{phase}", *extra],
                cwd=workdir, capture_output=True, text=True,
                timeout=budget_s, env=env)
            wall = round(time.monotonic() - t0, 1)
            cdir = os.path.join(workdir, "store", "campaigns",
                                f"survival-{phase}")
            recs = []
            with open(os.path.join(cdir, "cells.jsonl")) as f:
                for ln in f:
                    try:
                        recs.append(json.loads(ln))
                    except ValueError:
                        pass
            ev = [r for r in recs if r.get("event")]
            outcomes = [r for r in recs if not r.get("event")]
            out[phase] = {
                "wall_s": wall, "exit": p.returncode,
                "cells": len(outcomes),
                "ok": sum(1 for r in outcomes
                          if r.get("outcome") is True),
                "leases": sum(1 for e in ev
                              if e["event"] == "lease"),
                "steals": sum(1 for e in ev
                              if e["event"] == "lease-failed"),
                "syncs_ok": sum(1 for e in ev
                                if e["event"] == "artifact-sync"
                                and e.get("status") == "ok"),
                "syncs_failed": sum(1 for e in ev
                                    if e["event"] == "artifact-sync"
                                    and e.get("status") == "failed"),
                "mirrored": sum(1 for r in outcomes
                                if r.get("synced") is True
                                and os.path.isdir(str(r.get("path")))),
            }
        if out["clean"]["wall_s"]:
            out["chaos_overhead_x"] = round(
                out["chaos"]["wall_s"] / out["clean"]["wall_s"], 2)
        from jepsen_tpu.fleet import ledger as fledger
        led = fledger.Ledger(os.path.join(workdir, "store",
                                          "compile_ledger"))
        st = led.stats()
        jax_cache = os.path.join(workdir, "store", "compile_ledger",
                                 fledger.JAX_CACHE_DIR)
        # the workers compile in their own stores; the jax cache that
        # matters for warm restarts is per worker store
        caches = [os.path.join(workdir, d, "compile_ledger",
                               fledger.JAX_CACHE_DIR)
                  for d in ("wstore-clean", "wstore-chaos")] \
            + [jax_cache]
        out["warm_restart"] = {
            "cold_wall_s": st.get("cold_wall_s"),
            "warm_wall_s": st.get("warm_wall_s"),
            "warm_vs_clean_x": round(
                out["warm"]["wall_s"] / out["clean"]["wall_s"], 2)
            if out["clean"]["wall_s"] else None,
            "jax_cache_files": sum(
                len(files) for c in caches if os.path.isdir(c)
                for _, _, files in os.walk(c)),
        }
        return out
    except Exception as exc:  # noqa: BLE001 - numbers, not crashes
        return {"error": repr(exc)[:300]}


def _ha_takeover_rung(time_limit_s=2, budget_s=900):
    """Coordinator failover (jepsen_tpu.fleet.ha): the rung-10 2-seed
    register matrix on 2 loopback workers, two ways:

      clean        coordinator HA on (lease 3 s), no faults: the
                   lease-renewal plane's price on the fleet wall
      kill         the ``coordinator-kill`` chaos fault SIGKILLs the
                   active coordinator right after a seeded lease
                   grant; a standby process tails the journal, fences
                   the corpse, and finishes the campaign

    Reported: detection+takeover latency (SIGKILL, stamped by the
    chaos die-once marker, to the standby's first post-takeover
    coordinator-lease grant), cells re-leased vs lost after the kill,
    and the kill-soak wall vs the clean HA wall. Self-contained and
    never fatal: a failover regression must show up as numbers (or an
    error field), not break the bench."""
    import os
    import subprocess
    import tempfile
    try:
        repo = os.path.dirname(os.path.abspath(__file__))
        workdir = tempfile.mkdtemp(prefix="jepsen-ha-takeover-")
        env = {**os.environ, "PYTHONPATH": repo, "JAX_PLATFORMS": "cpu"}
        out = {"matrix": "workload=register x seeds=2",
               "time_limit_s": time_limit_s, "coordinator_lease_s": 3}
        base = [sys.executable, "-m", "jepsen_tpu", "campaign",
                "--no-ssh", "--time-limit", str(time_limit_s),
                "--axis", "workload=register", "--seeds", "2",
                "--parallel", "2", "--workers", "local,local",
                "--lease", "300", "--max-leases", "5",
                "--coordinator-lease-s", "3", "--takeover-grace-s", "2"]

        def read_journal(cid):
            recs = []
            path = os.path.join(workdir, "store", "campaigns", cid,
                                "cells.jsonl")
            with open(path) as f:
                for ln in f:
                    try:
                        recs.append(json.loads(ln))
                    except ValueError:
                        pass
            return recs

        # clean: HA on, nobody dies -- the renewal plane's price
        t0 = time.monotonic()
        p = subprocess.run(base + ["--campaign-id", "ha-clean"],
                           cwd=workdir, capture_output=True, text=True,
                           timeout=budget_s, env=env)
        clean_wall = round(time.monotonic() - t0, 1)
        recs = read_journal("ha-clean")
        out["clean"] = {
            "wall_s": clean_wall, "exit": p.returncode,
            "ok": sum(1 for r in recs if not r.get("event")
                      and r.get("outcome") is True),
            "renewals": sum(1 for r in recs
                            if r.get("event") == "coordinator-lease"),
        }

        # kill: chaos SIGKILLs the coordinator; a standby takes over
        t0 = time.monotonic()
        coord = subprocess.Popen(
            base + ["--chaos-profile", "coordinator-kill:7",
                    "--campaign-id", "ha-kill"],
            cwd=workdir, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL, env=env)
        cdir = os.path.join(workdir, "store", "campaigns", "ha-kill")
        deadline = time.monotonic() + 60
        while not os.path.exists(os.path.join(cdir, "campaign.json")) \
                and time.monotonic() < deadline:
            time.sleep(0.2)
        standby = subprocess.run(
            base + ["--standby", "--campaign-id", "ha-kill"],
            cwd=workdir, capture_output=True, text=True,
            timeout=budget_s, env=env)
        coord.wait(timeout=budget_s)
        kill_wall = round(time.monotonic() - t0, 1)

        from jepsen_tpu.analysis.fleetmodel import parse_t
        recs = read_journal("ha-kill")
        takeover_i, takeover = next(
            ((i, r) for i, r in enumerate(recs)
             if r.get("event") == "coordinator-takeover"), (None, None))
        # the chaos die-once marker is written (flush+fsync)
        # immediately before the SIGKILL: its mtime IS the kill stamp
        marker = os.path.join(cdir, "chaos-coordinator-kill")
        kill_t = os.path.getmtime(marker) if os.path.exists(marker) \
            else None
        first_grant_t = next(
            (parse_t(r.get("t")) for r in recs[takeover_i or 0:]
             if r.get("event") == "coordinator-lease"
             and r.get("epoch") == (takeover or {}).get("epoch")), None)
        outcomes = [r for r in recs if not r.get("event")]
        terminal = {str(r.get("cell")) for r in outcomes
                    if r.get("outcome") != "aborted"}
        releases = sum(1 for i, r in enumerate(recs)
                       if r.get("event") == "lease"
                       and takeover_i is not None and i > takeover_i)
        out["kill"] = {
            "wall_s": kill_wall,
            "coordinator_exit": coord.returncode,   # -9: chaos landed
            "standby_exit": standby.returncode,
            "takeover": takeover is not None,
            "takeover_epoch": (takeover or {}).get("epoch"),
            "detect_takeover_s": round(
                parse_t(takeover.get("t")) - kill_t, 1)
            if takeover is not None and kill_t
            and parse_t(takeover.get("t")) else None,
            "kill_to_first_grant_s": round(first_grant_t - kill_t, 1)
            if first_grant_t and kill_t else None,
            "cells_releases_after_takeover": releases,
            "cells_lost": 2 - len(terminal),
            "kill_vs_clean_x": round(kill_wall / clean_wall, 2)
            if clean_wall else None,
        }
        return out
    except Exception as exc:  # noqa: BLE001 - numbers, not crashes
        return {"error": repr(exc)[:300]}


def _searchplan_rung(keys=4, bursts=6):
    """Search-plan reduction (jepsen_tpu.analysis.searchplan): the
    same quiescent multi-key cas-register batch checked with planning
    on and off, reporting

      segments                sub-searches the planner produced
      est_configs             planner's estimate, planned vs unplanned
      configs_explored        ACTUAL configs, planned vs unplanned
      wall_s                  device wall, planned vs unplanned
      planner_s / frac        the analyzer's own cost and its share of
                              the planned path's end-to-end time

    Each key is `bursts` concurrent write||write bursts separated by
    sealed quiescent writes, with one crashed (:info) read per burst
    and a STALE final read: the history is invalid, so both paths run
    a full exhaustion proof — and the flat one must carry every
    subset of the forever-open crashed reads (they are optional to
    linearize at every config, ~2^bursts distinct configs), while the
    planner elides them as search-dead and proves each tiny segment
    in isolation. The stale value is one actually written earlier, so
    the state-abstraction fast path can't shortcut either side.
    Self-contained and never fatal: a planner regression must show up
    as numbers (or an error field), not break the throughput bench."""
    try:
        from jepsen_tpu.analysis import searchplan
        from jepsen_tpu.models import model_spec
        from jepsen_tpu.parallel import check_batch_encoded
        spec = model_spec("cas-register")

        def key_hist():
            evs = []
            i = 0

            def ev(t, p, f, v):
                nonlocal i
                evs.append({"type": t, "process": p, "f": f,
                            "value": v, "index": i})
                i += 1

            for j in range(bursts):
                x = j * 10
                ev("invoke", 0, "write", x)
                ev("invoke", 1, "write", x + 1)
                ev("ok", 0, "write", x)
                ev("ok", 1, "write", x + 1)
                ev("invoke", 100 + j, "read", None)  # client times out:
                ev("info", 100 + j, "read", None)    # open forever
                ev("invoke", 0, "write", x + 5)   # sealing quiescent
                ev("ok", 0, "write", x + 5)       # write closes burst
            ev("invoke", 2, "read", None)
            ev("ok", 2, "read", 0)                # stale read: invalid
            return evs

        hists = [key_hist() for _ in range(keys)]
        out = {"keys": keys, "ops_per_key": len(hists[0]) // 2}

        # unplanned: today's default per-key batch
        pairs_off = [spec.encode(hv) for hv in hists]
        t0 = time.monotonic()
        r_off = check_batch_encoded(spec, pairs_off)
        out["wall_s_unplanned"] = round(time.monotonic() - t0, 3)

        # planned: segment each key at sealed quiescent cuts, one batch
        t0 = time.monotonic()
        all_segs = []
        spans = []
        est_planned = 0
        for hv in hists:
            segs, _info = searchplan.segment_events(spec, hv,
                                                    min_segment=1)
            spans.append((len(all_segs), len(segs)))
            all_segs += segs
            est_planned += sum(s.est_configs for s in segs)
        planner_s = time.monotonic() - t0
        pairs_on = [spec.encode(s.events) for s in all_segs]
        t0 = time.monotonic()
        r_on = check_batch_encoded(spec, pairs_on)
        wall_on = time.monotonic() - t0
        out.update({
            "segments": len(all_segs),
            "est_configs": {
                "planned": est_planned,
                "unplanned": sum(searchplan.estimate_configs(hv)
                                 for hv in hists)},
            "configs_explored": {
                "planned": sum(int(r.get("configs_explored") or 0)
                               for r in r_on),
                "unplanned": sum(int(r.get("configs_explored") or 0)
                                 for r in r_off)},
            "wall_s_planned": round(wall_on, 3),
            "planner_s": round(planner_s, 4),
            "planner_frac": round(planner_s / max(1e-9,
                                                  planner_s + wall_on),
                                  4),
            "verdicts_equal": (
                [r.get("valid") for r in r_off]
                == [searchplan.merge_segment_results(
                    r_on[s:s + c]).get("valid")
                    for s, c in spans]),
        })
        out["reduction"] = round(
            out["configs_explored"]["unplanned"]
            / max(1, out["configs_explored"]["planned"]), 2)
        return out
    except Exception as exc:  # noqa: BLE001 - numbers, not crashes
        return {"error": repr(exc)[:300]}


#: simulated per-op client latency for the obs-overhead rung, seconds.
#: 0.5 ms is CONSERVATIVE: the reference framework's ops cross SSH to
#: real database processes (network RTT alone is 0.1-1 ms; device-model
#: ops are far slower), so a clean-run denominator built from 0.5 ms
#: ops overstates the telemetry plane's relative cost, never hides it.
OBS_RUNG_OP_S = 0.0005


def _obs_overhead_rung(n_ops=4000, concurrency=8, pairs=6):
    """Telemetry-plane overhead (jepsen_tpu.obs): the same fixed-op
    run with obs OFF vs obs ON — where ON means the full fleet plane:
    per-op trace spans, metrics, AND the incremental crash-safe
    journals at the shipped default flush cadence. The client costs
    OBS_RUNG_OP_S per op (see above: conservative vs any real op) at a
    realistic concurrency (real campaigns run 5-64 workers; at
    concurrency 2 the interpreter loop is artificially
    dispatch-latency-bound and every microsecond of main-loop work
    triples through a GIL convoy), which makes ``overhead_frac`` the
    plane's share of a representative clean-run wall clock. One extra
    OFF/ON pair runs with the noop client — ops that cost literally
    nothing — and is reported as the ``stress_*`` detail: the
    instrumentation's worst case against a degenerate denominator,
    tracked but not the goal.

    Methodology: OFF/ON runs strictly interleaved, overhead computed
    from the per-variant MINIMUM. The shared CI/dev boxes this runs on
    show hypervisor-steal noise far larger than the effect (identical
    runs vary by 2-3x minutes apart); under additive load noise the
    minimum is the standard quiet-floor estimator, and interleaving
    keeps a slow stretch from landing entirely on one variant.
    Goal: overhead < 5%."""
    import tempfile

    try:
        from jepsen_tpu import checker as cc
        from jepsen_tpu import client as jclient
        from jepsen_tpu import core, store
        from jepsen_tpu import generator as gen
        from jepsen_tpu.os import noop as os_noop

        class _DelayClient(jclient.Client):
            def invoke(self, test, op):
                time.sleep(OBS_RUNG_OP_S)
                out = dict(op)
                out["type"] = "ok"
                return out

            def reusable(self, test):
                return True

        def build(obs_on, delay):
            return {
                "name": "bench-obs-overhead",
                "nodes": ["n1"], "concurrency": concurrency,
                "ssh": {"dummy?": True}, "os": os_noop,
                "client": _DelayClient() if delay else jclient.noop,
                "checker": cc.unbridled_optimism(),
                "generator": gen.clients(gen.limit(
                    n_ops, gen.repeat({"f": "read"}))),
                # default telemetry-flush-ms (500): the rung measures
                # the plane as shipped, journals included
                "obs?": obs_on,
            }

        def run_one(obs_on, delay=True):
            t0 = time.perf_counter()
            t = core.run(core.prepare_test(build(obs_on, delay)))
            assert t["results"]["valid"] is True
            return time.perf_counter() - t0, t

        saved = store.base_dir
        off_runs, on_runs = [], []
        with tempfile.TemporaryDirectory() as tmp:
            store.base_dir = tmp
            try:
                run_one(False)          # warm both code paths once
                run_one(True)
                for _ in range(pairs):
                    off_runs.append(run_one(False)[0])
                    s, t_on = run_one(True)
                    on_runs.append(s)
                stress_off = run_one(False, delay=False)[0]
                stress_on = run_one(True, delay=False)[0]
                trace_p = store.path(t_on, "trace.jsonl")
                trace_events = sum(1 for _ in open(trace_p)) \
                    if trace_p and __import__("os").path.exists(
                        trace_p) else None
            finally:
                store.base_dir = saved
        off_s, on_s = min(off_runs), min(on_runs)
        overhead = (on_s - off_s) / off_s if off_s > 0 else None
        return {
            "n_ops": n_ops, "pairs": pairs,
            "op_cost_s": OBS_RUNG_OP_S,
            "off_s": round(off_s, 4),
            "off_runs": [round(x, 3) for x in off_runs],
            "on_s": round(on_s, 4),
            "on_runs": [round(x, 3) for x in on_runs],
            "trace_events": trace_events,
            "overhead_frac": (round(overhead, 4)
                              if overhead is not None else None),
            "stress_off_s": round(stress_off, 4),
            "stress_on_s": round(stress_on, 4),
            "stress_overhead_frac": round(
                (stress_on - stress_off) / stress_off, 4)
            if stress_off > 0 else None,
            "goal": "< 0.05",
            "goal_met": (overhead is not None and overhead < 0.05),
        }
    except Exception as exc:  # noqa: BLE001 - numbers, not crashes
        return {"error": repr(exc)}


def _introspection_overhead_rung(pairs=5, n_ops=2000):
    """Device-search introspection overhead (rung 12): the same
    fixed cas-register device search with the progress telemetry —
    per-dispatch progress-tensor reads (explored / frontier / depth
    ride ONE batched device_get), heartbeat trace events, padding
    accounting, and the crash-safe journal flushes — fully ON
    (tracer + registry + journals, the plane as shipped) vs obs OFF
    entirely. OFF/ON runs strictly interleaved with the overhead
    computed from per-variant MINIMA (rung 11's quiet-floor
    estimator: hypervisor-steal noise on shared boxes is 2-3x the
    effect). The detail records the search's explored configs and
    its device duty cycle (wgl.device_busy_s / measured wall) so the
    perf trajectory restarts from a measured baseline. Goal: < 5%."""
    import os
    import tempfile

    try:
        from jepsen_tpu import obs
        from jepsen_tpu.checker import jax_wgl
        from jepsen_tpu.models import cas_register_spec
        from jepsen_tpu.simulate import random_history

        hist = random_history(random.Random(1212), "cas-register",
                              n_procs=16, n_ops=n_ops, crash_p=0.02)
        e, st = cas_register_spec.encode(hist)
        # compile outside the timed pairs; 1-iteration dispatch cap so
        # every run pays one heartbeat-bearing dispatch PER ITERATION
        # instead of finishing inside one chunk (the overhead under
        # test is per-dispatch — this is its worst case)
        kw = {"timeout_s": 120.0, "chunk_iters": 1}
        jax_wgl.check_encoded(cas_register_spec, e, st, max_configs=1)

        def run_off():
            # mask the bench's own global registry: OFF means the
            # engines resolve NO sinks at capture
            with obs.bind(None, None):
                t0 = time.perf_counter()
                r = jax_wgl.check_encoded(cas_register_spec, e, st,
                                          **kw)
                return time.perf_counter() - t0, r, None

        def run_on():
            with tempfile.TemporaryDirectory() as tmp:
                tr, reg = obs.Tracer(), obs.Registry()
                tr.attach_journal(os.path.join(
                    tmp, "trace.jsonl.journal"))
                reg.attach_journal(os.path.join(
                    tmp, "metrics.json.journal"))
                with obs.bind(tr, reg):
                    t0 = time.perf_counter()
                    r = jax_wgl.check_encoded(cas_register_spec, e,
                                              st, **kw)
                    dt = time.perf_counter() - t0
                tr.close_journal()
                reg.close_journal()
                return dt, r, reg

        off_runs, on_all = [], []
        run_off()            # warm both code paths once, untimed
        run_on()
        for _ in range(pairs):
            s_off, r_off, _ = run_off()
            off_runs.append(s_off)
            on_all.append(run_on())
        off_s = min(off_runs)
        # the min-wall ON run is the quiet-floor sample; its OWN
        # registry supplies the busy wall so the duty cycle pairs
        # numerator and denominator from the same run
        on_s, best_on, best_reg = min(on_all, key=lambda t: t[0])
        on_runs = [t[0] for t in on_all]
        overhead = (on_s - off_s) / off_s if off_s > 0 else None
        busy = float(best_reg.counter_value(
            "wgl.device_busy_s", engine="jax-wgl")) \
            if best_reg is not None else None
        # chunk wall + per-phase breakdown from the SAME best run's
        # registry: busy is now the device-compute bracket, so the
        # chunk_s sum supplies the old full-dispatch-wall context and
        # phase_s says where the difference went
        intro = {}
        if best_reg is not None:
            try:
                from jepsen_tpu.obs.merge import introspection_summary
                intro = introspection_summary(best_reg.snapshot())
            except Exception:  # noqa: BLE001
                intro = {}
        return {
            "n_ops": n_ops, "ops": len(e), "pairs": pairs,
            "valid": best_on.get("valid") if best_on else None,
            "explored_configs": best_on.get("configs_explored")
            if best_on else None,
            "chunks": int(best_reg.counter_value(
                "wgl.chunks", engine="jax-wgl"))
            if best_reg is not None else None,
            "device_busy_s": round(busy, 4)
            if busy is not None else None,
            "duty_cycle": round(busy / on_s, 4)
            if busy is not None and on_s > 0 else None,
            "chunk_s": intro.get("chunk_s"),
            "phase_s": intro.get("phase_s"),
            "off_s": round(off_s, 4),
            "off_runs": [round(x, 3) for x in off_runs],
            "on_s": round(on_s, 4),
            "on_runs": [round(x, 3) for x in on_runs],
            "overhead_frac": (round(overhead, 4)
                              if overhead is not None else None),
            "goal": "< 0.05",
            "goal_met": (overhead is not None and overhead < 0.05),
        }
    except Exception as exc:  # noqa: BLE001 - numbers, not crashes
        return {"error": repr(exc)}


def _service_throughput_rung(clients=8, per_client=3, bursts=10):
    """Batched multi-tenant checking (rung 13): N concurrent clients
    driving ONE live server over loopback, the same mixed
    valid/invalid submissions checked with coalescing OFF then ON
    (the per-request "coalesce" payload knob against a
    coalescing-enabled server, so transport, admission, and engine
    stay constant across modes — only the batching differs).

    Each mode's fan-out runs twice and the SECOND pass is the timed
    one: the solo path and every pow-2 batch width the coalescer
    closes compile on the first pass, so the timed numbers compare
    steady-state dispatch, not compiles. Reports checks/s, p50/p99
    verdict latency, batches/segments/occupancy from the service
    coalesce counters, the device duty cycle (wgl.device_busy_s over
    the mode wall, the PR 13 metrics plane), and verdict equality
    across modes. Self-contained and never fatal."""
    import json as _json
    import threading
    import urllib.request

    try:
        from jepsen_tpu import obs, web
        from jepsen_tpu.fleet import service

        service.reset()
        # every loopback client shares one caller id (no tokens):
        # budgets must admit the whole fan-out without shedding
        service.configure(budgets={"concurrent-checks": 4 * clients,
                                   "queue-depth": 8 * clients})
        server = web.serve({"ip": "127.0.0.1", "port": 0})
        port = server.server_address[1]
        url = f"http://127.0.0.1:{port}/api/check"

        def hist(bad):
            ev = []

            def e(t, p, f, v):
                ev.append({"type": t, "process": p, "f": f,
                           "value": v})

            for j in range(bursts):
                x = j * 10
                e("invoke", 0, "write", x)
                e("invoke", 1, "write", x + 1)
                e("ok", 0, "write", x)
                e("ok", 1, "write", x + 1)
                e("invoke", 0, "write", x + 5)
                e("ok", 0, "write", x + 5)
            e("invoke", 2, "read", None)
            # the stale read targets a genuinely-written value, so
            # invalidity needs the real search, not the abstraction
            e("ok", 2, "read", 0 if bad else (bursts - 1) * 10 + 5)
            return ev

        # shape-identical across clients (one compile bucket, the
        # cross-tenant ledger-hit case); every 4th client submits a
        # violation so batches mix valid and invalid
        hists = [[hist(bad=(c % 4 == 3)) for _ in range(per_client)]
                 for c in range(clients)]

        def post(h, coalesce):
            body = _json.dumps({"history": h, "model": "cas-register",
                                "coalesce": coalesce,
                                "timeout-s": 120}).encode()
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            t0 = time.monotonic()
            with urllib.request.urlopen(req, timeout=600) as r:
                got = _json.loads(r.read())
            return time.monotonic() - t0, got["valid"]

        def reg_busy():
            reg = obs.registry()
            if reg is None:
                return 0.0
            return sum(v for k, v in
                       reg.snapshot()["counters"].items()
                       if k.startswith("wgl.device_busy_s"))

        def reg_chunk():
            # full dispatch-chunk wall (the wgl.chunk_s histogram):
            # busy above is the device-compute bracket, chunk is the
            # old whole-chunk meaning, busy <= chunk always
            reg = obs.registry()
            if reg is None:
                return 0.0
            return sum(float((h or {}).get("sum") or 0.0)
                       for k, h in
                       reg.snapshot()["histograms"].items()
                       if k.startswith("wgl.chunk_s"))

        def fan_out(flag):
            lat = [[None] * per_client for _ in range(clients)]
            vrd = [[None] * per_client for _ in range(clients)]
            errors = []

            def one_client(c):
                for i in range(per_client):
                    try:
                        lat[c][i], vrd[c][i] = post(hists[c][i], flag)
                    except Exception as exc:  # noqa: BLE001
                        errors.append(repr(exc)[:120])

            threads = [threading.Thread(target=one_client, args=(c,))
                       for c in range(clients)]
            t0 = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return (time.monotonic() - t0, lat, vrd, errors)

        out = {"clients": clients, "per_client": per_client,
               "ops_per_check": 6 * bursts + 1}
        verdicts = {}
        for mode, flag in (("off", False), ("on", True)):
            fan_out(flag)                     # warm pass: compiles
            st0 = service.coalescer().stats()
            busy0 = reg_busy()
            chunk0 = reg_chunk()
            wall, lat, vrd, errors = fan_out(flag)
            st1 = service.coalescer().stats()
            busy = reg_busy() - busy0
            chunk = reg_chunk() - chunk0
            flat = sorted(x for row in lat for x in row
                          if x is not None)
            n = len(flat)
            verdicts[mode] = [v for row in vrd for v in row]
            out[mode] = {
                "wall_s": round(wall, 3),
                "checks_per_s": round(n / wall, 2) if wall else None,
                "p50_ms": round(flat[n // 2] * 1000, 1) if n else None,
                "p99_ms": round(flat[min(n - 1, int(0.99 * n))]
                                * 1000, 1) if n else None,
                "errors": errors[:5],
                "batches": st1["batches"] - st0["batches"],
                "segments": st1["segments"] - st0["segments"],
                "device_busy_s": round(busy, 3),
                "chunk_s": round(chunk, 3),
                "duty_cycle": round(busy / wall, 4) if wall else None,
            }
        st = service.coalescer().stats()
        out["occupancy"] = st["occupancy"]
        out["verdicts_identical"] = verdicts["on"] == verdicts["off"]
        out["violations_detected"] = sum(
            1 for v in verdicts["on"] if v is False)
        if out["off"]["checks_per_s"] and out["on"]["checks_per_s"]:
            out["coalesce_speedup_x"] = round(
                out["on"]["checks_per_s"]
                / out["off"]["checks_per_s"], 2)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/metrics",
                timeout=30) as r:
            text = r.read().decode()
        out["metrics_exposed"] = (
            "jepsen_service_coalesce_batches" in text
            and "jepsen_service_coalesce_occupancy" in text
            and "jepsen_admission_shed_total" in text)
        server.shutdown()
        service.reset()
        return out
    except Exception as exc:  # noqa: BLE001 - numbers, not crashes
        return {"error": repr(exc)[:300]}


def _txn_scale_rung(n_txns=16384, appends_per_txn=7, chunk=1024,
                    budget_s=900):
    """Transactional cycle checking at scale (rung 15): a serial
    list-append history of >= 1e5 micro-ops checked two ways -- one
    offline ``cycle/`` analysis of the whole history (the
    cycle-checked txns/s headline) and the family="txn" monitor core
    driven chunk by chunk (per-chunk latency plus the squaring-pass
    ledger against the from-scratch closure every chunk would
    otherwise pay -- the incrementality contract, measured).

    This is the scale/family the WGL engine is refused at outright:
    multi-key txn micro-ops have no sequential model, so the rung
    records the model registry's refusal verbatim instead of timing a
    search that cannot exist. The duty cycle comes from the
    ``txn.closure_busy_s`` counter the closure kernels bracket (the
    same metrics plane as ``wgl.device_busy_s``), over each mode's
    wall. Self-contained and never fatal."""
    import numpy as _np

    try:
        from jepsen_tpu import cycle, obs
        from jepsen_tpu.monitor import engine as mengine
        from jepsen_tpu.monitor.txn import TxnCheck

        # serial multi-key history: each txn reads its key's committed
        # prefix THEN appends (the read stays cross-txn: observing your
        # own in-txn appends is legal but exercises nothing), keys
        # retire after txns_per_key txns so reads stay short
        txns_per_key = 8
        events = []
        t = 0
        for i in range(n_txns):
            k = f"k{i // txns_per_key}"
            base = (i % txns_per_key) * appends_per_txn
            mops = ([["r", k, None]]
                    + [["append", k, base + j + 1]
                       for j in range(appends_per_txn)])
            done = [list(m) for m in mops]
            done[0] = ["r", k, list(range(1, base + 1))]
            events.append({"type": "invoke", "f": "txn",
                           "process": i % 8, "time": t, "value": mops})
            events.append({"type": "ok", "f": "txn",
                           "process": i % 8, "time": t + 1,
                           "value": done})
            t += 2
        micro_ops = n_txns * (appends_per_txn + 1)
        out = {"txns": n_txns, "micro_ops": micro_ops,
               "events": len(events), "chunk": chunk}

        # the WGL side of the fork in the road: no sequential model
        # exists for multi-key txn micro-ops, so the linearizability
        # path refuses at the registry, before any search
        try:
            from jepsen_tpu.models import model_spec
            model_spec("txn-append")
            out["wgl_refusal"] = None
        except KeyError as exc:
            out["wgl_refusal"] = str(exc)[:160]

        def busy():
            reg = obs.registry()
            if reg is None:
                return 0.0
            return sum(v for key, v in
                       reg.snapshot()["counters"].items()
                       if key.startswith("txn.closure_busy_s"))

        # OFFLINE: one full analysis -- the txns/s headline
        b0, p0 = busy(), cycle.closure_passes()
        t0 = time.monotonic()
        res = mengine.check_txn_prefix(events, "append")
        off_wall = time.monotonic() - t0
        off_busy = busy() - b0
        out["offline"] = {
            "valid": res.get("valid"),
            "wall_s": round(off_wall, 3),
            "txns_per_s": round(n_txns / off_wall, 1)
            if off_wall else None,
            "micro_ops_per_s": round(micro_ops / off_wall, 1)
            if off_wall else None,
            "closure_passes": cycle.closure_passes() - p0,
            "device_busy_s": round(off_busy, 3),
            "duty_cycle": round(off_busy / off_wall, 4)
            if off_wall else None,
        }

        # STREAMING: the monitor core, chunk txns at a time, frontier
        # resident across chunks
        core = TxnCheck(workload="append")
        lat = []
        b0, p0 = busy(), cycle.closure_passes()
        t0 = time.monotonic()
        exhausted = False
        for start in range(0, len(events), 2 * chunk):
            for ev in events[start:start + 2 * chunk]:
                core.offer(ev)
            c0 = time.monotonic()
            r = core.check()
            lat.append(time.monotonic() - c0)
            if r.get("valid") is not True:
                out["streaming_error"] = {
                    "valid": r.get("valid"),
                    "anomaly_types": r.get("anomaly_types")}
                break
            if time.monotonic() - t0 > budget_s:
                exhausted = True
                break
        inc_wall = time.monotonic() - t0
        inc_passes = cycle.closure_passes() - p0
        inc_busy = busy() - b0
        lat_s = sorted(lat)
        out["streaming"] = {
            "chunks": len(lat),
            "txns_checked": core.n_txns,
            "wall_s": round(inc_wall, 3),
            "budget_exhausted": exhausted,
            "chunk_p50_ms": round(lat_s[len(lat_s) // 2] * 1e3, 1)
            if lat_s else None,
            "chunk_max_ms": round(lat_s[-1] * 1e3, 1)
            if lat_s else None,
            "closure_passes": inc_passes,
            "closure_rebuilds": core.frontier.rebuilds,
            "device_busy_s": round(inc_busy, 3),
            "duty_cycle": round(inc_busy / inc_wall, 4)
            if inc_wall else None,
        }

        # the counterfactual: one from-scratch closure at the final
        # padded size, timed once -- what EVERY chunk would pay
        # without the resident frontier
        n_pad = max(64, int(core.frontier.n_pad))
        scratch_steps = max(1, int(_np.ceil(_np.log2(max(2, n_pad)))))
        adj = core.frontier._adj[:core.frontier.n, :core.frontier.n]
        p1 = cycle.closure_passes()
        s0 = time.monotonic()
        cycle.transitive_closure(adj)
        scratch_wall = time.monotonic() - s0
        out["scratch"] = {
            "n_pad": n_pad,
            "closure_s": round(scratch_wall, 3),
            "closure_passes": cycle.closure_passes() - p1,
            "per_chunk_passes_if_rebuilt": scratch_steps,
            "total_passes_if_rebuilt": scratch_steps * len(lat),
        }
        if inc_passes:
            out["passes_saved_x"] = round(
                scratch_steps * len(lat) / inc_passes, 2)
        out["goal"] = ("valid at >= 1e5 micro-ops; incremental passes "
                       "< per-chunk from-scratch total")
        out["goal_met"] = bool(
            not exhausted
            and out["offline"]["valid"] is True
            and "streaming_error" not in out
            and inc_passes < scratch_steps * max(1, len(lat)))
        return out
    except Exception as exc:  # noqa: BLE001 - numbers, not crashes
        return {"error": repr(exc)[:300]}


def _error_headline(msg):
    """The zero-value headline shape every bench failure path emits
    (one definition so error lines can't drift from success lines)."""
    return json.dumps({"metric": "ops verified/sec (cas-register)",
                       "value": 0.0, "unit": "ops/s",
                       "vs_baseline": 0.0, "error": msg})


def _device_preflight(timeout_s=240, tries=2):
    """The remote-TPU tunnel can go fully down for hours (observed:
    >2 h in round 5), and jax backend init then HANGS rather than
    erroring. Probe it in a killable child first so a dead tunnel
    yields a parseable headline line instead of an eternal hang.
    One retry distinguishes a transient stall (e.g. another process
    briefly holding the chip) from a real outage."""
    import subprocess
    err = None
    for _ in range(tries):
        try:
            p = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s, capture_output=True, text=True)
            if p.returncode == 0:
                return None
            err = (p.stderr.strip()[-300:] or "backend init failed")
        except subprocess.TimeoutExpired:
            err = (f"backend init hung >{timeout_s}s twice "
                   "(tunnel down or chip held)")
    return err


def main():
    err = _device_preflight()
    if err:
        print(_error_headline(f"TPU unavailable: {err}"))
        return
    # bind a metrics registry for the whole bench: the engines'
    # search-telemetry heartbeats (chunk latencies, states explored,
    # dedup-table load) accumulate in it and ship inside the headline
    # detail blob, so every reported rate carries its own evidence
    from jepsen_tpu import obs
    _obs_reg = obs.Registry()
    with obs.bind(None, _obs_reg):
        _bench_body(_obs_reg)


def _bench_body(_obs_reg):
    # persistent compile cache: the kernel's shape buckets are designed
    # for reuse, and remote-compile latency is highly variable (~20-70 s
    # cold for the big FIFO shapes) -- without this, compile variance
    # can flip the pass/fail rungs
    import jax
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

    from jepsen_tpu.checker import jax_wgl, wgl
    from jepsen_tpu.models import (cas_register_spec, fifo_queue_spec,
                                   mutex_spec)
    from jepsen_tpu.parallel import check_batch_encoded
    from jepsen_tpu.simulate import corrupt, random_history

    rungs = {}
    rng = random.Random(45100)

    # -- rungs 1 + 2: cas-register, single + batched ---------------------
    # (drawn FIRST from the seeded rng: the same histories as round 1's
    # bench, so the headline rate is comparable across rounds)
    spec = cas_register_spec
    n_keys, ops_per_key = 32, 200
    hists = []
    for k in range(n_keys):
        hist = random_history(rng, "cas-register", n_procs=8,
                              n_ops=ops_per_key, crash_p=0.02)
        if k % 8 == 7:
            hist = corrupt(rng, hist)
        hists.append(hist)
    hist3 = random_history(rng, "mutex", n_procs=64, n_ops=10_000,
                           crash_p=0.02)
    hist4 = random_history(rng, "fifo-queue", n_procs=6, n_ops=150,
                           crash_p=0.02)
    hist5 = random_history(rng, "cas-register", n_procs=64, n_ops=10_000,
                           crash_p=0.05)
    pairs = [spec.encode(hist) for hist in hists]
    total_ops = sum(len(e) for e, _ in pairs)

    t0 = time.monotonic()
    base_results = [wgl.check_encoded(spec, e, st, max_configs=2_000_000)
                    for e, st in pairs]
    cpu_s = time.monotonic() - t0

    # rung 1: one history at a time on device (warm, after compile)
    e1, st1 = pairs[0]
    jax_wgl.check_encoded(spec, e1, st1)
    t0 = time.monotonic()
    r1 = jax_wgl.check_encoded(spec, e1, st1)
    rung1_s = time.monotonic() - t0
    rungs["1-cas-single"] = {
        "ops": len(e1), "device_s": round(rung1_s, 3),
        "valid": r1["valid"],
    }

    # rung 2: the whole key batch in one device program (kept at 32 keys
    # for round-over-round comparability; the oracle agreement check
    # anchors correctness)
    # TWO warmups: compaction points are timing-dependent, so one run
    # does not visit every (batch-width, frontier-width) kernel variant
    # -- a first timed run once paid 22 s of mid-run compiles that a
    # second warm run avoided entirely (4.3 s)
    check_batch_encoded(spec, pairs)
    check_batch_encoded(spec, pairs)
    dev_s, runs2, dev_results = timed3(
        lambda: check_batch_encoded(spec, pairs))
    agree = sum(1 for a, b in zip(base_results, dev_results)
                if a["valid"] == b["valid"])
    dev_rate = total_ops / dev_s
    cpu_rate = total_ops / cpu_s
    rungs["2-cas-multikey"] = {
        "keys": n_keys, "total_ops": total_ops,
        "device_s": round(dev_s, 3), "device_s_runs": runs2,
        "cpu_oracle_s": round(cpu_s, 3),
        "device_rate": round(dev_rate, 1),
        "cpu_rate": round(cpu_rate, 1),
        "verdicts_agree": f"{agree}/{n_keys}",
    }

    # rung 2b (the HEADLINE since round 3): 256 keys, same per-key
    # shape. The key axis is nearly free on device -- that is the point
    # of the batched kernel -- so the throughput headline uses the wide
    # batch; vs_baseline divides by the single-thread CPU oracle rate
    # measured on the 32-key subset above (same workload distribution;
    # a full 256-key oracle run would blow the bench budget).
    rng2 = random.Random(20260730)
    hists2b = list(hists)
    for k in range(len(hists), 256):
        h = random_history(rng2, "cas-register", n_procs=8,
                           n_ops=ops_per_key, crash_p=0.02)
        if k % 8 == 7:
            h = corrupt(rng2, h)
        hists2b.append(h)
    pairs2b = [spec.encode(h) for h in hists2b]
    total2b = sum(len(e) for e, _ in pairs2b)
    check_batch_encoded(spec, pairs2b)        # compile warmups (x2:
    check_batch_encoded(spec, pairs2b)        # see rung 2)
    dev2b_s, runs2b, res2b = timed3(
        lambda: check_batch_encoded(spec, pairs2b))
    rate2b = total2b / dev2b_s
    rungs["2b-cas-256key"] = {
        "keys": 256, "total_ops": total2b,
        "device_s": round(dev2b_s, 3), "device_s_runs": runs2b,
        "device_rate": round(rate2b, 1),
        "invalid_keys": sum(1 for r in res2b if r["valid"] is False),
        "unknown_keys": sum(1 for r in res2b
                            if r["valid"] == "unknown"),
    }

    # rung 2c: K=1024 and the keys-vs-throughput CURVE (VERDICT r3 next
    # #7: the claimed "throughput via the key axis" trade reported as a
    # measured curve, not a point). Same per-key workload distribution
    # as 2/2b; the 32- and 256-key points reuse those rungs' runs.
    hists2c = list(hists2b)
    for k in range(len(hists2c), 1024):
        h = random_history(rng2, "cas-register", n_procs=8,
                           n_ops=ops_per_key, crash_p=0.02)
        if k % 8 == 7:
            h = corrupt(rng2, h)
        hists2c.append(h)
    pairs2c = [spec.encode(h) for h in hists2c]
    total2c = sum(len(e) for e, _ in pairs2c)
    check_batch_encoded(spec, pairs2c)        # compile warmups (x2:
    check_batch_encoded(spec, pairs2c)        # see rung 2)
    dev2c_s, runs2c, res2c = timed3(
        lambda: check_batch_encoded(spec, pairs2c))
    rate2c = total2c / dev2c_s
    rungs["2c-cas-1024key"] = {
        "keys": 1024, "total_ops": total2c,
        "device_s": round(dev2c_s, 3), "device_s_runs": runs2c,
        "device_rate": round(rate2c, 1),
        "invalid_keys": sum(1 for r in res2c if r["valid"] is False),
        "unknown_keys": sum(1 for r in res2c
                            if r["valid"] == "unknown"),
        "curve_ops_per_s": {"32": round(dev_rate, 1),
                            "256": round(rate2b, 1),
                            "1024": round(rate2c, 1)},
    }

    # -- rung 3: mutex, high contention ----------------------------------
    e3, st3 = mutex_spec.encode(hist3)
    jax_wgl.check_encoded(mutex_spec, e3, st3, timeout_s=120)  # warm
    t0 = time.monotonic()
    r3 = jax_wgl.check_encoded(mutex_spec, e3, st3, timeout_s=60)
    d3 = time.monotonic() - t0
    rungs["3-mutex"] = {
        "ops": len(e3), "procs": 64,
        "device_s": round(d3, 1), "device_valid": r3["valid"],
        "device_iterations": r3.get("iterations"),
    }

    # -- rung 4: FIFO queue ----------------------------------------------
    e4, st4 = fifo_queue_spec.encode(hist4)
    t0 = time.monotonic()
    r4 = jax_wgl.check_encoded(fifo_queue_spec, e4, st4, timeout_s=60)
    d4 = time.monotonic() - t0
    rungs["4-fifo-queue"] = {
        "ops": len(e4), "procs": 6,
        "device_s": round(d4, 1), "device_valid": r4["valid"],
        "engine": r4.get("engine"),
    }

    # rung 4b: info-free FIFO at 25x the old search's reach -- decided
    # by the exact aspect (bad-pattern) fast path
    hist4b = random_history(rng, "fifo-queue", n_procs=16, n_ops=5000,
                            crash_p=0.0)
    e4b, st4b = fifo_queue_spec.encode(hist4b)
    t0 = time.monotonic()
    r4b = jax_wgl.check_encoded(fifo_queue_spec, e4b, st4b)
    rungs["4b-fifo-aspect-5k"] = {
        "ops": len(e4b), "procs": 16,
        "device_s": round(time.monotonic() - t0, 2),
        "device_valid": r4b["valid"], "engine": r4b.get("engine"),
    }

    # rung 4c: 10k-op FIFO with ~500 crashed ops INCLUDING info
    # dequeues, decided exactly by the round-3 closure+matching aspect
    # (round 2 punted all info-dequeue histories to the search, which
    # capped out near 200 ops)
    hist4c = random_history(rng, "fifo-queue", n_procs=64, n_ops=10_000,
                            crash_p=0.05)
    e4c, st4c = fifo_queue_spec.encode(hist4c)
    t0 = time.monotonic()
    r4c = jax_wgl.check_encoded(fifo_queue_spec, e4c, st4c)
    rungs["4c-fifo-info-10k"] = {
        "ops": len(e4c), "procs": 64,
        "infos": int((~e4c.is_ok).sum()),
        "info_dequeues": sum(1 for o in hist4c if o["type"] == "info"
                             and o["f"] == "dequeue"),
        "device_s": round(time.monotonic() - t0, 2),
        "device_valid": r4c["valid"], "engine": r4c.get("engine"),
    }

    # rung 4d: the SEARCH engine itself (fast path disabled) on a
    # 2k-op info-dequeue-bearing FIFO history: the witness-order hint +
    # junk-enqueue prune let the greedy rollout walk an explicit
    # linearization, so the B&B decides in a handful of iterations
    # where round 2's kernel capped out near 200 ops
    import dataclasses
    forced = dataclasses.replace(fifo_queue_spec, fast_check=None)
    hist4d = random_history(rng, "fifo-queue", n_procs=16, n_ops=2000,
                            crash_p=0.05)
    e4d, st4d = forced.encode(hist4d)
    jax_wgl.check_encoded(forced, e4d, st4d, timeout_s=120)  # warm compile
    t0 = time.monotonic()
    r4d = jax_wgl.check_encoded(forced, e4d, st4d, timeout_s=60)
    d4d = time.monotonic() - t0
    assert r4d.get("engine") == "jax-wgl", r4d
    rungs["4d-fifo-info-search-2k"] = {
        "ops": len(e4d), "procs": 16,
        "infos": int((~e4d.is_ok).sum()),
        "device_s": round(d4d, 2), "device_valid": r4d["valid"],
        "engine": r4d.get("engine"),
        "device_iterations": r4d.get("iterations"),
        "search_goal_met": bool(r4d["valid"] in (True, False)
                                and d4d < 60),
    }

    # -- rung 5: the stretch goal ----------------------------------------
    # warm the compile first: the goal gates on wall clock, and remote
    # compile stalls (observed 60+ s once) are not the search's time
    e5, st5 = cas_register_spec.encode(hist5)
    jax_wgl.check_encoded(cas_register_spec, e5, st5, timeout_s=120)
    t0 = time.monotonic()
    r5 = jax_wgl.check_encoded(cas_register_spec, e5, st5, timeout_s=120)
    d5 = time.monotonic() - t0
    rungs["5-cas-10k-64proc"] = {
        "ops": len(e5), "procs": 64,
        "infos": int((~e5.is_ok).sum()),
        "device_s": round(d5, 1), "device_valid": r5["valid"],
        "device_iterations": r5.get("iterations"),
    }

    # -- rung 6: the linear engine's home turf ---------------------------
    # knossos's competition races linear and wgl as co-equal engines
    # (reference checker.clj:199-202). On long LOW-concurrency
    # crash-free histories the event sweep's config set stays tiny and
    # the CPU linear engine beats the device search outright (which
    # pays W*n tensor work per iteration); this rung proves the racer
    # genuinely wins somewhere (VERDICT r3 weak #5).
    from jepsen_tpu.checker import linear
    hist6 = random_history(random.Random(606), "cas-register",
                           n_procs=2, n_ops=50_000, crash_p=0.0)
    e6, st6 = cas_register_spec.encode(hist6)
    t0 = time.monotonic()
    r6l = linear.check_encoded(cas_register_spec, e6, st6,
                               max_configs=200_000)
    d6l = time.monotonic() - t0
    jax_wgl.check_encoded(cas_register_spec, e6, st6, max_configs=1)
    t0 = time.monotonic()
    r6d = jax_wgl.check_encoded(cas_register_spec, e6, st6,
                                timeout_s=90)
    d6d = time.monotonic() - t0
    rungs["6-linear-home-turf"] = {
        "ops": len(e6), "procs": 2, "crash_p": 0.0,
        "linear_s": round(d6l, 2), "linear_valid": r6l["valid"],
        "device_s": round(d6d, 2), "device_valid": r6d["valid"],
        "linear_wins": bool(r6l["valid"] in (True, False)
                            and (d6l < d6d
                                 or r6d["valid"] not in (True, False))),
    }

    # -- rung 0: the BASELINE primary metric -----------------------------
    # max single-key history length decidable in 60 s, measured to the
    # engine's ACTUAL limit: exponential growth until a size fails the
    # budget, then bisection to tighten the decided/undecided bracket.
    # (Round 3 walked a hardcoded ladder whose top rung decided in
    # 7.6 s, so the reported "max" was the ladder's end, not the
    # engine's limit -- VERDICT r3 weak #1.) Each shape bucket is
    # compile-warmed with a 1-iteration probe before its first timed
    # run so growth gates on search time, not compile stalls; the
    # engine's adaptive dispatch quantum enforces the wall budget.
    import dataclasses
    fifo_search = dataclasses.replace(fifo_queue_spec, fast_check=None)
    BUDGET_S = 60.0
    # per-row cap on total probe time. 600 s leaves room for one
    # monster tunnel stall (observed: a single 256k-request dispatch
    # running 418 s against a 60 s budget) plus the retry + bisection
    # probes that rescue the bracket afterwards
    ROW_WALL_S = 600.0
    rows0 = (
        # (row key, model name, spec, procs, crash_p, start, cap)
        ("cas-register", "cas-register", cas_register_spec, 64, 0.05,
         16_000, 1_024_000),
        ("mutex", "mutex", mutex_spec, 64, 0.05, 8_000, 1_024_000),
        # the aspect row's old 1.6M cap was the reported max (3.4 s
        # decided -- the cap, not the engine, bound; VERDICT r4 #4).
        # Measured scaling: the aspect check runs ~2.2 s per 1M ops
        # (60 s budget would bind near ~26M), but host-side Python
        # history generation + encode costs ~30 s per 1M ops, so the
        # per-row wall binds first around 12.8M -- the honest,
        # recorded failure mode (gen_s per probe documents it)
        ("fifo-queue-aspect", "fifo-queue", fifo_queue_spec, 64, 0.05,
         200_000, 25_600_000),
        # the raw SEARCH engine on info-dequeue-bearing FIFO histories
        # (aspect disabled, like rung 4d): the honest search-path row
        ("fifo-queue-search", "fifo-queue", fifo_search, 16, 0.05,
         2_000, 256_000),
    )
    maxlen = {}
    for mi, (row, mname, mspec, procs, crash_p, start, cap) in \
            enumerate(rows0):

        def attempt(n_ops, _mi=mi, _mname=mname, _mspec=mspec,
                    _procs=procs, _crash=crash_p):
            # one deterministic sub-seed per (row, size): growth and
            # bisection probes never shift each other's histories, and
            # rows stay independent across rounds
            seed = 77000 + _mi * 1_000_003 + n_ops
            tg = time.monotonic()
            h0 = random_history(random.Random(seed), _mname,
                                n_procs=_procs, n_ops=n_ops,
                                crash_p=_crash)
            e0, st0 = _mspec.encode(h0)
            # history generation + encode is host-side Python and grows
            # linearly; at the aspect row's tens-of-millions-of-ops
            # scale it becomes the binding constraint, so it is
            # recorded separately from the (budgeted) check time
            gen_s = round(time.monotonic() - tg, 1)
            try:
                # 1-iteration probe: compiles the bucket's kernels
                jax_wgl.check_encoded(_mspec, e0, st0, max_configs=1)
                t0 = time.monotonic()
                r0 = jax_wgl.check_encoded(_mspec, e0, st0,
                                           timeout_s=BUDGET_S)
                dt0 = time.monotonic() - t0
            except Exception as exc:  # noqa: BLE001 - e.g. device OOM
                return {"n_ops": n_ops, "ops": len(e0), "s": None,
                        "gen_s": gen_s, "ok": False,
                        "error": repr(exc)[:200]}
            return {"n_ops": n_ops, "ops": len(e0),
                    "s": round(dt0, 1), "gen_s": gen_s,
                    "ok": bool(r0["valid"] in (True, False)
                               and dt0 <= BUDGET_S),
                    "engine": r0.get("engine", "jax-wgl"),
                    "table_load": r0.get("table_load"),
                    "table_insert_failures":
                        r0.get("table_insert_failures"),
                    "error": r0.get("error")}

        t_row = time.monotonic()

        def attempt_fair(n_ops):
            """One retry when a not-ok probe either errored outright
            (s None: the remote-compile service 500s flakily -- a
            rehearsal recorded one as a fail bracket for a shape that
            had compiled fine minutes earlier) or grossly overshot
            the budget (>1.5x) -- the adaptive quantum calibrates
            from measured per-iteration wall, so a mid-probe tunnel
            hiccup can burn the window without giving the search a
            fair 60 s; deciding on retry proves 60 s decidability
            honestly. Skipped once the row wall is spent (a retry
            would double the overrun)."""
            a = attempt(n_ops)
            # deterministic resource failures are not flaky: retrying
            # an OOM-sized probe would just OOM again and eat the row
            # wall the bisection needs
            oom = any(t in (a.get("error") or "")
                      for t in ("RESOURCE_EXHAUSTED", "Out of memory",
                                "out of memory"))
            flaky = a["s"] is None or a["s"] > BUDGET_S * 1.5
            if (not a["ok"] and flaky and not oom
                    and time.monotonic() - t_row < ROW_WALL_S):
                a = attempt(n_ops)
            return a
        good, bad = None, None
        n = start
        while n <= cap and time.monotonic() - t_row < ROW_WALL_S:
            a = attempt_fair(n)
            if a["ok"]:
                good, n = a, n * 2
            else:
                bad = a
                break
        # bisect the [good, bad] bracket until it's tight (<15%); the
        # bracket>2000 guard keeps mid strictly inside the bracket at
        # the 1000-op probe granularity (otherwise the clamp can pin
        # mid to good's own size and the loop would spin re-running
        # the identical probe until the row wall)
        while (good is not None and bad is not None
               and bad["n_ops"] - good["n_ops"] > 2000
               and bad["n_ops"] > good["n_ops"] * 1.15
               and time.monotonic() - t_row < ROW_WALL_S):
            mid = round((good["n_ops"] + bad["n_ops"]) / 2, -3)
            mid = int(min(max(mid, good["n_ops"] + 1000),
                          bad["n_ops"] - 1000))
            a = attempt_fair(mid)
            if a["ok"]:
                good = a
            else:
                bad = a
        entry = None
        if good is not None:
            entry = {"ops": good["ops"], "requested": good["n_ops"],
                     "s": good["s"], "gen_s": good["gen_s"],
                     "engine": good["engine"]}
            if good.get("table_load") is not None:
                entry["table_load"] = good["table_load"]
                entry["table_insert_failures"] = \
                    good["table_insert_failures"]
            if bad is not None:
                entry["first_fail"] = {
                    "requested": bad["n_ops"], "ops": bad["ops"],
                    "s": bad["s"], "gen_s": bad.get("gen_s"),
                    "error": bad["error"]}
            elif good["n_ops"] * 2 > cap:
                entry["cap_reached"] = cap
            else:
                # the per-row wall bound before the 60 s check budget
                # did; gen_s in the probes shows whether host-side
                # history generation (not the engine) ate the wall
                entry["row_budget_exhausted"] = True
        elif bad is not None:
            entry = {"ops": 0, "first_fail": {
                "requested": bad["n_ops"], "ops": bad["ops"],
                "s": bad["s"], "gen_s": bad.get("gen_s"),
                "error": bad["error"]}}
        maxlen[row] = entry
    rungs["0-maxlen-60s"] = maxlen

    # streaming-monitor rung: the BENCH trajectory's headline for the
    # online path is detection latency, not throughput -- how long
    # after a violating op lands does the monitor's latch flip. Runs
    # after the timed device rungs (its chunk checks share the chip)
    rungs["7-monitor-detection"] = _monitor_rung()

    # fleet rung: cold-vs-warm wall clock of the same matrix in two
    # SEPARATE scheduler processes; warm must report ledger hits > 0
    # (runs on CPU in subprocesses -- see the rung's docstring)
    rungs["8-fleet-reuse"] = _fleet_reuse_rung()

    # search-plan rung: quiescent-cut slicing must beat the flat batch
    # on explored configs, with the planner itself in the noise
    rungs["9-searchplan"] = _searchplan_rung()

    # fleet-survival rung: the chaos soak's wall-clock price vs the
    # clean fleet, plus the warm-restart win from the persistent jax
    # compilation cache (CPU subprocesses; see the rung's docstring)
    rungs["10-fleet-survival"] = _fleet_survival_rung()

    # obs-overhead rung: the fleet telemetry plane (tracer + metrics +
    # crash-safe journals) must stay under 5% of clean-run wall clock
    # on the interpreter hot path (pure host work; chip not involved)
    rungs["11-obs-overhead"] = _obs_overhead_rung()

    # introspection-overhead rung: the search-progress telemetry
    # (progress-tensor device reads + heartbeats + padding accounting
    # + journal flushes) must stay under 5% of the same search with
    # obs off, and the detail re-baselines explored-configs and the
    # device duty cycle for the optimization arc
    rungs["12-introspection-overhead"] = _introspection_overhead_rung()

    # service-throughput rung: the cross-tenant coalescer must turn
    # queued /api/check wait into device occupancy — coalescing ON
    # strictly beats OFF on checks/s at concurrency >= 8 with
    # per-submission verdicts identical to the solo path
    rungs["13-service-throughput"] = _service_throughput_rung()

    # ha-takeover rung: kill the fleet coordinator mid-campaign and
    # measure how fast a standby fences it and finishes the work —
    # detection+takeover latency, re-leased vs lost cells, and the
    # kill-soak wall against the clean HA wall (rung 10's matrix)
    rungs["14-ha-takeover"] = _ha_takeover_rung()

    # txn-scale rung: the transactional family at the scale WGL is
    # refused at — cycle-checked txns/s over >= 1e5 micro-ops offline,
    # then the streaming monitor core over the same history: per-chunk
    # latency and the squaring-pass ledger vs the from-scratch closure
    # every chunk would otherwise pay, duty cycle from the
    # closure-busy counter
    rungs["15-txn-scale"] = _txn_scale_rung()

    # stream-monitor rung: 100 concurrent monitored streams, flat
    # re-search vs device-resident frontiers riding coalesced batches
    # — monitored-ops/s, detection p50/p99, duty cycle, and the
    # owners >= 2 batch-sharing evidence
    rungs["16-stream-monitor"] = _stream_monitor_rung()

    # CPU oracles race in parallel subprocesses AFTER all device
    # measurements (their CPU load would pollute the device numbers);
    # total added wall time <= one 60 s budget
    oracles = {"3": OracleRace("mutex", hist3),
               "4": OracleRace("fifo-queue", hist4),
               "4c": OracleRace("fifo-queue", hist4c),
               "4d": OracleRace("fifo-queue", hist4d),
               "5": OracleRace("cas-register", hist5)}
    for key, rung in (("3", "3-mutex"), ("4", "4-fifo-queue"),
                      ("4c", "4c-fifo-info-10k"),
                      ("4d", "4d-fifo-info-search-2k"),
                      ("5", "5-cas-10k-64proc")):
        o = oracles[key].result()
        rungs[rung]["cpu_s"] = round(o["s"], 1)
        rungs[rung]["cpu_valid"] = o["valid"]
    rungs["5-cas-10k-64proc"]["goal_met"] = bool(
        r5["valid"] in (True, False) and d5 < 60
        and rungs["5-cas-10k-64proc"]["cpu_valid"] == "unknown")

    if agree != n_keys:
        print(_error_headline(f"verdict mismatch: {agree}/{n_keys}"))
        return

    headline_rung, headline = max(
        (("2b-cas-256key", rate2b), ("2c-cas-1024key", rate2c)),
        key=lambda kv: kv[1])
    head = {
        "metric": "ops verified/sec (cas-register)",
        "value": round(headline, 1),
        "unit": "ops/s",
        "vs_baseline": round(headline / cpu_rate, 3),
        "headline_rung": headline_rung,
    }
    # environment fingerprint: every detail blob (and trend record)
    # says WHERE it was measured, so a cross-host comparison can
    # refuse instead of flagging hardware differences as regressions
    env = None
    try:
        from jepsen_tpu.obs import trend as obs_trend
        env = obs_trend.fingerprint()
        obs_trend.record(rungs, fp=env, label="bench")
    except Exception:  # noqa: BLE001 - the headline must print
        pass
    # detail first, short headline-only line LAST: the driver captures
    # the output's tail, and the detail blob once pushed the headline
    # fields out of it (BENCH_r04 "parsed": null)
    print(json.dumps({**head, "detail": rungs,
                      "environment": env,
                      # whole-bench scope: includes the compile
                      # warm-up dispatches the timed rungs exclude, so
                      # chunk_s tails here overstate the measured runs
                      "metrics_scope": "whole-bench-incl-warmups",
                      "metrics": _obs_reg.snapshot()}))
    print(json.dumps(head))


if __name__ == "__main__":
    main()
