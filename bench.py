"""Benchmark: ops verified/sec on CAS-register histories (BASELINE.json).

Measures the device WGL engine on the BASELINE config ladder's first two
rungs: (1) single ~200-op cas-register histories, (2) a multi-key batch
(jepsen.independent-style) checked in one vmapped program. The baseline is
the sequential CPU oracle (our knossos stand-in, checker/wgl.py) on the
same histories.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import random
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0])


def main():
    from jepsen_tpu.checker import wgl
    from jepsen_tpu.models import cas_register_spec
    from jepsen_tpu.parallel import check_batch_encoded
    from jepsen_tpu.simulate import corrupt, random_history

    spec = cas_register_spec
    rng = random.Random(45100)
    n_keys, ops_per_key = 32, 200
    hists = []
    for k in range(n_keys):
        hist = random_history(rng, "cas-register", n_procs=8,
                              n_ops=ops_per_key, crash_p=0.02)
        if k % 8 == 7:
            hist = corrupt(rng, hist)
        hists.append(hist)
    pairs = [spec.encode(hist) for hist in hists]
    total_ops = sum(len(e) for e, _ in pairs)

    # CPU baseline: sequential WGL oracle over all keys
    t0 = time.monotonic()
    base_results = [wgl.check_encoded(spec, e, st) for e, st in pairs]
    cpu_s = time.monotonic() - t0
    cpu_rate = total_ops / cpu_s

    # Device: warm up with the identical shape bundle (compile), then measure
    check_batch_encoded(spec, pairs)
    t0 = time.monotonic()
    dev_results = check_batch_encoded(spec, pairs)
    dev_s = time.monotonic() - t0
    dev_rate = total_ops / dev_s

    agree = sum(1 for a, b in zip(base_results, dev_results)
                if a["valid"] == b["valid"])
    if agree != n_keys:
        print(json.dumps({"metric": "ops verified/sec (cas-register)",
                          "value": 0.0, "unit": "ops/s",
                          "vs_baseline": 0.0,
                          "error": f"verdict mismatch: {agree}/{n_keys}"}))
        return

    print(json.dumps({
        "metric": "ops verified/sec (cas-register)",
        "value": round(dev_rate, 1),
        "unit": "ops/s",
        "vs_baseline": round(dev_rate / cpu_rate, 3),
        "detail": {
            "keys": n_keys, "ops_per_key": ops_per_key,
            "total_ops": total_ops,
            "device_s": round(dev_s, 3), "cpu_oracle_s": round(cpu_s, 3),
            "cpu_oracle_rate": round(cpu_rate, 1),
            "verdicts_agree": agree,
        }}))


if __name__ == "__main__":
    main()
